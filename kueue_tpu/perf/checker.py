"""Rangespec checker.

Equivalent of the reference's test/performance/scheduler/checker
(checker_test.go over default_rangespec.yaml:1-30): assert the recorded
statistics stay inside accepted bounds.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.perf.runner import RunResult


@dataclass
class RangeSpec:
    # Backend family the HARDWARE-DEPENDENT bounds (wall time,
    # snapshot-build ms, phase p99 ms) were calibrated on; "" means the
    # spec is backend-agnostic. A run on a different backend (or one
    # that fell back to CPU) REFUSES comparison instead of reporting a
    # regression that never happened — see refuse_cross_backend and the
    # ROADMAP bench-env note (BENCH_r05 vs r04 are not comparable).
    backend: str = ""
    max_wall_s: float = 0.0   # 0 = unchecked (hardware-dependent)
    # workload class -> max average time-to-admission (seconds)
    wl_class_max_avg_tta_s: dict = field(default_factory=dict)
    # cq class -> min average usage pct
    cq_class_min_usage_pct: dict = field(default_factory=dict)
    min_admitted: int = 0
    # Snapshot-build latency bounds (incremental journal-replay
    # snapshots): regression guards on the per-cache.snapshot() build
    # cost. 0 = unchecked. Unlike the queueing-dynamics bounds these are
    # host-compute bounds — set them with generous headroom over a
    # measured round so only an order-of-regression (e.g. the maintainer
    # silently falling back to full rebuilds every cycle) trips them.
    max_snapshot_build_p50_ms: float = 0.0
    max_snapshot_build_p99_ms: float = 0.0
    # Per-cycle phase p99 bounds (cycle flight recorder histograms),
    # same philosophy as the snapshot-build bounds: host-compute
    # regression guards with generous headroom, checked ONLY for phases
    # that recorded samples (a CPU-only run has no solver phases; the
    # default config's min_heads gate can keep the solver dark).
    max_phase_p99_ms: dict = field(default_factory=dict)
    # Compile-storm immunity bound (solver/COMPILE.md): program variants
    # first executed inside a measured cycle. Backend-independent (a
    # count, not a latency), so it survives cross-backend refusal.
    # None = unchecked; 0 = the steady-state contract (every variant
    # warmed by the compile governor before the clock started).
    max_mid_traffic_compiles: Optional[int] = None
    # Device-vs-CPU speedup floor for a bench regime row (the ROADMAP
    # item-2 coverage contract: no bench regime where the router must
    # pick CPU). Hardware-dependent by definition — a spec carrying it
    # MUST declare its backend, and cross-backend runs refuse instead of
    # judging (BENCH_r05 ran cpu_fallback; its rows are not comparable).
    # 0 = unchecked.
    min_device_speedup: float = 0.0
    # Steady-state transport bounds (decision-only fetch / donated
    # uploads): max bytes per device cycle on the wire, averaged over
    # the run's dispatches/collects. The BOUNDS are calibrated per
    # deployment shape so a spec carrying them declares its backend
    # (tunnel transports frame differently); a transport regression —
    # e.g. the fetch silently reverting to dense [W,...] arrays —
    # fails loudly instead of hiding in a wall-time wash. 0 =
    # unchecked.
    max_fetch_bytes_per_cycle: int = 0
    max_upload_bytes_per_cycle: int = 0


# --- device-witness debt manifest -----------------------------------------
#
# Every rangespec/SLO gate that REFUSES on cpu_fallback (the bench-env
# honesty policy) is a bound that has NEVER been witnessed on a device
# backend: the PR-9 PREEMPT_SPEEDUP_FLOORS, the tenant-storm device
# route gate, the r05 e2e re-baseline, and the fused-route transport
# floors. The registry below consolidates every refusal recorded during
# a perf/bench run into one manifest the JSON artifacts carry, so a
# future run on a real device knows exactly which gates it must
# witness — instead of re-deriving the debt from scattered
# rangespec_refused fields.

_WITNESS_DEBT: list = []


def record_refusal(context: str, kind: str, reason: str,
                   spec_backend: str = "") -> dict:
    """Record one refused comparison into the device-witness debt
    manifest. Returns the entry (already appended). Deduplicates on
    (context, kind) — a gate refused twice in one run is one debt."""
    entry = {"context": context, "kind": kind, "reason": reason,
             "calibrated_backend": spec_backend}
    for e in _WITNESS_DEBT:
        if e["context"] == context and e["kind"] == kind:
            return e
    _WITNESS_DEBT.append(entry)
    return entry


def witness_debt() -> list:
    """The consolidated manifest of every gate this process refused to
    judge (copy — callers may serialize it into artifacts)."""
    return [dict(e) for e in _WITNESS_DEBT]


def reset_witness_debt() -> None:
    _WITNESS_DEBT.clear()


def check_device_speedup(speedup: float, spec: RangeSpec,
                         backend: Optional[dict]) -> tuple:
    """Judge one bench regime row's device-vs-CPU speedup against the
    spec's floor. Returns (ok, note): ok is None when the comparison is
    refused (cross-backend / CPU fallback — the PR-6 honesty contract),
    True/False otherwise, with the note carrying the refusal reason or
    the violation text."""
    refusal = refuse_cross_backend(spec, backend)
    if refusal is not None:
        return None, refusal
    if spec.min_device_speedup and speedup <= spec.min_device_speedup:
        return False, (f"device speedup {speedup:.2f}x <= floor "
                       f"{spec.min_device_speedup:.2f}x — a CPU-won "
                       f"regime the router must route away from")
    return True, ""


def default_rangespec() -> RangeSpec:
    """The reference's accepted bounds (default_rangespec.yaml:8-30).
    Wall-time/CPU/RSS bounds are hardware-specific and unchecked here;
    the queueing-dynamics bounds carry over because the virtual clock
    reproduces the reference's arrival/runtime schedule. The
    snapshot-build bounds are ours (no reference equivalent): at the
    default 30-CQ shape a journal-replay advance measured ~0.7-0.8 ms
    p50 / 6-11 ms p99 on a contended 2-core box (PR 2 measurement
    round), so 3/30 ms trips only on a maintainer regression (e.g.
    silently serving full rebuilds every cycle, ~an order of magnitude
    slower), not machine noise."""
    return RangeSpec(
        wl_class_max_avg_tta_s={"large": 11.0, "medium": 90.0, "small": 233.0},
        cq_class_min_usage_pct={"cq": 55.0},
        max_snapshot_build_p50_ms=3.0,
        max_snapshot_build_p99_ms=30.0,
        # Phase p99 bounds at the default 30-CQ shape (bucket-estimated
        # from cycle_phase_seconds; see PR-4). Host phases measured
        # sub-ms p50 — 100 ms trips only on an order-of-regression
        # (e.g. the nominate loop going quadratic). Device round-trip
        # phases get 1 s: a warm dispatch is ms-scale, but a missed
        # warmup bucket legitimately carries one local compile.
        max_phase_p99_ms={"snapshot": 100.0, "nominate": 100.0,
                          "encode": 100.0, "route": 100.0,
                          "decode": 100.0, "apply": 100.0,
                          "requeue": 100.0, "dispatch": 1000.0,
                          "fetch": 1000.0},
    )


def north_star_rangespec() -> RangeSpec:
    """Bounds for the north-star scenario (50k pending x 2k CQs x 32
    flavors). No published reference queueing-dynamics bounds exist at
    this scale, so the spec carries only the backend-independent
    compile-storm contract: after the compile governor's pre-clock
    warmup, ZERO program variants may first execute inside a measured
    cycle (ROADMAP item 4 / solver/COMPILE.md). A violation means the
    bucket ladder missed a shape the traffic hit — a hot-path compile
    stall in production."""
    return RangeSpec(max_mid_traffic_compiles=0)


def refuse_cross_backend(spec: RangeSpec, backend: Optional[dict]) -> Optional[str]:
    """Bench-env honesty (ROADMAP bench-env note): numbers measured on
    different backends are not comparable, so a spec that declares the
    backend its bounds were calibrated on refuses to judge a run from
    another one. Returns the refusal reason, or None when the
    comparison is sound (backend-agnostic spec, or matching backend
    with no CPU fallback)."""
    if not spec.backend or backend is None:
        return None
    run_backend = backend.get("backend", "unknown")
    if backend.get("cpu_fallback") and spec.backend != "cpu":
        return (f"rangespec calibrated on {spec.backend!r} but the run "
                f"fell back to CPU — cross-backend comparison refused")
    if run_backend != spec.backend:
        return (f"rangespec calibrated on {spec.backend!r} but the run "
                f"used {run_backend!r} — cross-backend comparison "
                f"refused")
    return None


@dataclass
class SLOSpec:
    """Service-level bounds for one sim scenario (sim/scenarios.py +
    sim/SCENARIOS.md): where RangeSpec bounds a perf run's host-compute
    statistics, SLOSpec bounds a scenario's QUEUEING behavior — per-
    priority-class p99 time-to-admission under the scenario's traffic,
    degradation-ladder recovery after its storm, requeue amplification
    of its eviction waves, and the zero-starvation invariant. Times are
    VIRTUAL seconds (FakeClock), so the bounds are backend-agnostic by
    default; a spec that also bounds wall behavior declares the backend
    it was calibrated on and cross-backend comparison is refused, same
    policy as RangeSpec (refuse_cross_backend works on both)."""
    backend: str = ""
    # priority class -> max p99 time-to-admission (virtual seconds)
    class_max_p99_tta_s: dict = field(default_factory=dict)
    min_admitted: int = 0
    # No workload still eligible at scenario end may be unadmitted
    # (result.starved lists offenders after the drain phase).
    zero_starvation: bool = True
    # Max cycles from storm end (the driver's phase-tag flip) back to
    # the ladder's normal rung. None = unchecked; a scenario whose
    # ladder never engaged recovers in 0 cycles by definition.
    max_ladder_recovery_cycles: Optional[int] = None
    # Max (admission grants + evictions) / (distinct admitted
    # workloads): bounds retry-storm churn. 0 = unchecked; 1.0 means
    # every workload admitted exactly once with no evictions.
    max_requeue_amplification: float = 0.0
    max_evictions: Optional[int] = None
    # Crash-restart durability (RESILIENCE.md §6): max VIRTUAL seconds
    # from a restore back to the next admission grant — the
    # recovery-to-first-admission SLO. Virtual time keeps it
    # backend-agnostic like every other SLOSpec bound. None =
    # unchecked; with a bound set, a scenario that restarted but never
    # admitted again is itself a violation.
    max_recovery_to_first_admission_s: Optional[float] = None
    # Query-plane read side (obs/queryplane.py + ISSUE 12): a scenario
    # that runs a read storm concurrently with its traffic gates the
    # read responses here. min_reads = the storm actually read (0 =
    # unchecked); max_read_staleness_generations bounds the WORST
    # structural-generation lag any response's token showed vs the live
    # cache at read time (0 = every read served the current structural
    # generation; None = unchecked — with a bound set, a run that
    # recorded no staleness samples is itself a violation).
    min_reads: int = 0
    max_read_staleness_generations: Optional[int] = None
    # Hot-standby failover (RESILIENCE.md §7): max VIRTUAL seconds
    # from a standby promotion back to the next admission grant — the
    # promotion-to-first-admission SLO, gated WELL UNDER the PR-10
    # cold-restore budget (the entire point of the warm follower).
    # None = unchecked; with a bound set, a scenario that promoted but
    # never admitted again is itself a violation.
    max_promotion_to_first_admission_s: Optional[float] = None
    # MultiKueue batched-column re-placement (ISSUE 13): max VIRTUAL
    # seconds from a worker-cluster loss to the LAST affected workload
    # re-reserving on a surviving cluster (the cluster_rebalance
    # scenario stamps result.replacement_latency_s). None = unchecked;
    # with a bound set, a run whose survivors never re-placed is
    # itself a violation.
    max_replacement_latency_s: Optional[float] = None
    # --- soak gates (sim/soak.py + ISSUE 18) ---------------------------
    # These judge the counters a composed multi-day run stamps on its
    # result, so one check_slo call renders the whole soak verdict.
    # The AgingWatch must end green: counters["aging"] (the gate() dict
    # the harness stamps) must exist with ok=True — no monitor leaking
    # or over-bound at run end. A run that never stamped the gate is
    # itself a violation (the watch was not sampled, not "green").
    require_aging_green: bool = False
    # Max per-class journey SLO burn rate at run end
    # (counters["journeys"]["burn_rates"], obs/journey.py: violation-
    # fraction EWMA / error budget — 1.0 burns exactly at budget).
    # None = unchecked; requires objectives set (harness
    # set_objectives). A run that stamps no burn rates while this
    # bound is set is a violation, not a vacuous pass — empty
    # evidence means the ledger went dark, not that nothing burned.
    max_journey_burn_rate: Optional[float] = None
    # Max program variants first executed inside a measured cycle AFTER
    # the soak's warm horizon (virtual day 1): the steady-state
    # compile-storm contract over a long composed run
    # (counters["mid_traffic_compiles_after_warm"]; 0 = the north-star
    # bound, None = unchecked). Solver-less runs stamp 0 honestly.
    max_mid_traffic_compiles_after_warm: Optional[int] = None
    # Teardown handout leak gate: counters["live_handouts_at_teardown"]
    # (stamped after manager shutdown) must be 0 — a long-lived run
    # may not strand snapshot borrows.
    require_zero_live_handouts: bool = False


def check_slo(result, spec: SLOSpec) -> list:
    """Evaluate a ScenarioResult (sim/scenarios.py) against its SLOSpec;
    returns violation strings (empty = all gates green). Callers should
    refuse cross-backend comparison first (refuse_cross_backend accepts
    an SLOSpec — same .backend contract as RangeSpec)."""
    violations = []
    if result.admitted < spec.min_admitted:
        violations.append(
            f"admitted {result.admitted} below minimum {spec.min_admitted}")
    for cls, bound in spec.class_max_p99_tta_s.items():
        p99 = result.class_p99_tta_s.get(cls)
        if p99 is None:
            violations.append(
                f"no admissions recorded for priority class {cls!r}")
        elif p99 > bound:
            violations.append(
                f"class {cls!r} p99 time-to-admission {p99:.1f}s "
                f"exceeds {bound:.1f}s")
    if spec.zero_starvation and result.starved:
        sample = ", ".join(sorted(result.starved)[:5])
        violations.append(
            f"{len(result.starved)} workload(s) starved (never admitted "
            f"while eligible): {sample}")
    if spec.max_ladder_recovery_cycles is not None:
        rec = result.ladder_recovery_cycles
        if rec is None:
            violations.append(
                "ladder engaged but never recovered to the normal rung")
        elif rec > spec.max_ladder_recovery_cycles:
            violations.append(
                f"ladder recovery took {rec} cycles, bound "
                f"{spec.max_ladder_recovery_cycles}")
    if spec.max_requeue_amplification \
            and result.requeue_amplification > spec.max_requeue_amplification:
        violations.append(
            f"requeue amplification {result.requeue_amplification:.2f} "
            f"exceeds {spec.max_requeue_amplification:.2f}")
    if spec.max_evictions is not None \
            and result.evictions > spec.max_evictions:
        violations.append(
            f"{result.evictions} evictions exceed bound "
            f"{spec.max_evictions}")
    if spec.max_recovery_to_first_admission_s is not None:
        restarts = getattr(result, "restarts", 0)
        recov = getattr(result, "recovery_to_first_admission_s", [])
        if restarts and len(recov) < restarts:
            violations.append(
                f"{restarts - len(recov)} of {restarts} restart(s) "
                "never re-admitted a workload")
        worst = max(recov) if recov else 0.0
        if worst > spec.max_recovery_to_first_admission_s:
            violations.append(
                f"recovery-to-first-admission {worst:.1f}s exceeds "
                f"{spec.max_recovery_to_first_admission_s:.1f}s")
    if spec.max_promotion_to_first_admission_s is not None:
        promotions = getattr(result, "promotions", 0)
        ttas = getattr(result, "promotion_to_first_admission_s", [])
        if promotions and len(ttas) < promotions:
            violations.append(
                f"{promotions - len(ttas)} of {promotions} "
                "promotion(s) never re-admitted a workload")
        worst = max(ttas) if ttas else 0.0
        if worst > spec.max_promotion_to_first_admission_s:
            violations.append(
                f"promotion-to-first-admission {worst:.1f}s exceeds "
                f"{spec.max_promotion_to_first_admission_s:.1f}s")
    if spec.min_reads:
        reads = getattr(result, "reads", 0)
        if reads < spec.min_reads:
            violations.append(
                f"query plane served {reads} reads, below the "
                f"{spec.min_reads} the storm was sized for")
    if spec.max_read_staleness_generations is not None:
        worst_lag = getattr(result, "read_staleness_generations", None)
        if worst_lag is None:
            violations.append(
                "read-staleness bound set but the run recorded no "
                "staleness samples (no stamped read responses)")
        elif worst_lag > spec.max_read_staleness_generations:
            violations.append(
                f"worst read staleness {worst_lag} structural "
                f"generation(s) exceeds bound "
                f"{spec.max_read_staleness_generations}")
    if spec.max_replacement_latency_s is not None:
        lat = getattr(result, "replacement_latency_s", None)
        if lat is None:
            violations.append(
                "re-placement bound set but the run recorded no "
                "re-placement (survivors never re-reserved)")
        elif lat > spec.max_replacement_latency_s:
            violations.append(
                f"cluster-loss re-placement took {lat:.1f}s, bound "
                f"{spec.max_replacement_latency_s:.1f}s")
    counters = getattr(result, "counters", {}) or {}
    if spec.require_aging_green:
        gate = counters.get("aging")
        if gate is None:
            violations.append(
                "aging gate required but the run stamped no "
                "counters['aging'] (AgingWatch never sampled)")
        elif not gate.get("ok"):
            bad = {name: gate["verdicts"].get(name, "?")
                   for name in gate.get("failing", [])}
            violations.append(f"aging gate red at run end: {bad}")
    if spec.max_journey_burn_rate is not None:
        rates = (counters.get("journeys") or {}).get("burn_rates") or {}
        if not rates:
            violations.append(
                "journey burn-rate bound set but the run stamped no "
                "counters['journeys']['burn_rates'] (ledger unpriced "
                "or lost across a restart)")
        for cls in sorted(rates):
            if rates[cls] > spec.max_journey_burn_rate:
                violations.append(
                    f"class {cls!r} journey SLO burn rate "
                    f"{rates[cls]:.2f} exceeds "
                    f"{spec.max_journey_burn_rate:.2f}")
    if spec.max_mid_traffic_compiles_after_warm is not None:
        compiles = counters.get("mid_traffic_compiles_after_warm")
        if compiles is None:
            violations.append(
                "post-warm compile bound set but the run stamped no "
                "counters['mid_traffic_compiles_after_warm']")
        elif compiles > spec.max_mid_traffic_compiles_after_warm:
            violations.append(
                f"{compiles} program variant(s) first executed inside "
                f"a cycle after the warm horizon (bound "
                f"{spec.max_mid_traffic_compiles_after_warm})")
    if spec.require_zero_live_handouts:
        handouts = counters.get("live_handouts_at_teardown")
        if handouts is None:
            violations.append(
                "teardown handout gate set but the run stamped no "
                "counters['live_handouts_at_teardown']")
        elif handouts:
            violations.append(
                f"{handouts} snapshot handout(s) still live at "
                "teardown (live_handouts != 0 after shutdown)")
    return violations


def journey_objectives(spec: SLOSpec) -> dict:
    """SLOSpec-derived objectives for the journey ledger's burn-rate
    evaluator (obs/journey.py + ISSUE 14): the per-class p99 TTA bounds
    a scenario gates on ARE the targets the live SLI stream is priced
    against — one source of truth, so a scenario's post-hoc SLO verdict
    and the live ``slo_burn_rate{class}`` gauge can never diverge on
    what "too slow" means. Returns {class: target_tta_seconds}."""
    return dict(spec.class_max_p99_tta_s)


def check(result: RunResult, spec: RangeSpec) -> list:
    violations = []
    if spec.max_wall_s and result.wall_s > spec.max_wall_s:
        violations.append(
            f"wall time {result.wall_s:.1f}s exceeds {spec.max_wall_s:.1f}s")
    if result.admitted < spec.min_admitted:
        violations.append(
            f"admitted {result.admitted} below minimum {spec.min_admitted}")
    for cls, bound in spec.wl_class_max_avg_tta_s.items():
        stats = result.class_stats.get(cls)
        if stats is None:
            violations.append(f"no stats recorded for workload class {cls!r}")
            continue
        if stats.avg > bound:
            violations.append(
                f"class {cls!r} avg time-to-admission {stats.avg:.1f}s "
                f"exceeds {bound:.1f}s")
    for cls, bound in spec.cq_class_min_usage_pct.items():
        usage = result.cq_class_avg_usage_pct.get(cls, 0.0)
        if usage < bound:
            violations.append(
                f"cq class {cls!r} avg usage {usage:.1f}% below {bound:.1f}%")
    if spec.max_snapshot_build_p50_ms \
            and result.snapshot_build_p50_ms > spec.max_snapshot_build_p50_ms:
        violations.append(
            f"snapshot build p50 {result.snapshot_build_p50_ms:.3f}ms "
            f"exceeds {spec.max_snapshot_build_p50_ms:.3f}ms")
    if spec.max_snapshot_build_p99_ms \
            and result.snapshot_build_p99_ms > spec.max_snapshot_build_p99_ms:
        violations.append(
            f"snapshot build p99 {result.snapshot_build_p99_ms:.3f}ms "
            f"exceeds {spec.max_snapshot_build_p99_ms:.3f}ms")
    for phase, bound in spec.max_phase_p99_ms.items():
        p99 = result.phase_p99_ms.get(phase)
        if p99 is not None and p99 > bound:
            violations.append(
                f"cycle phase {phase!r} p99 {p99:.3f}ms "
                f"exceeds {bound:.3f}ms")
    if spec.max_fetch_bytes_per_cycle \
            and result.fetch_bytes_per_cycle is not None \
            and result.fetch_bytes_per_cycle \
            > spec.max_fetch_bytes_per_cycle:
        violations.append(
            f"steady-state fetch {result.fetch_bytes_per_cycle:.0f} "
            f"bytes/cycle exceeds {spec.max_fetch_bytes_per_cycle} — "
            f"the decision-only fetch regressed toward dense tensors")
    if spec.max_upload_bytes_per_cycle \
            and result.upload_bytes_per_cycle is not None \
            and result.upload_bytes_per_cycle \
            > spec.max_upload_bytes_per_cycle:
        violations.append(
            f"steady-state upload {result.upload_bytes_per_cycle:.0f} "
            f"bytes/cycle exceeds {spec.max_upload_bytes_per_cycle}")
    if spec.max_mid_traffic_compiles is not None \
            and result.mid_traffic_compiles is not None \
            and result.mid_traffic_compiles > spec.max_mid_traffic_compiles:
        violations.append(
            f"{result.mid_traffic_compiles} program variant(s) first "
            f"executed inside a measured cycle (bound "
            f"{spec.max_mid_traffic_compiles}) — the warmup ladder "
            f"missed shape bucket(s) the traffic hit")
    return violations
