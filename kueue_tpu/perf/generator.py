"""Workload/queue generator for the perf harness.

Equivalent of the reference's test/performance/scheduler/generator
driven by default_generator_config.yaml:1-28: a class spec tree
(cohorts x queue sets x workload sets) expands into ResourceFlavor/
ClusterQueue/LocalQueue objects plus a time-ordered arrival schedule.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import ObjectMeta

RESOURCE = "cpu"  # abstract units (the reference uses 1-unit requests)
FLAVOR = "default"


@dataclass
class WorkloadClass:
    class_name: str
    runtime_ms: int
    priority: int
    request: int


@dataclass
class WorkloadSet:
    count: int
    creation_interval_ms: int
    workloads: list = field(default_factory=list)  # list[WorkloadClass]


@dataclass
class QueueClass:
    class_name: str
    count: int
    nominal_quota: int
    borrowing_limit: Optional[int] = None
    reclaim_within_cohort: str = api.PREEMPTION_ANY
    within_cluster_queue: str = api.PREEMPTION_LOWER_PRIORITY
    workloads_sets: list = field(default_factory=list)  # list[WorkloadSet]


@dataclass
class CohortClass:
    class_name: str
    count: int
    queues_sets: list = field(default_factory=list)  # list[QueueClass]


def default_generator_config() -> list:
    """The reference's default config: 5 cohorts x 6 CQs, per CQ
    350 small + 100 medium + 50 large => 15,000 workloads / 30 CQs
    (default_generator_config.yaml:1-28)."""
    return [CohortClass(class_name="cohort", count=5, queues_sets=[
        QueueClass(
            class_name="cq", count=6, nominal_quota=20, borrowing_limit=100,
            workloads_sets=[
                WorkloadSet(count=350, creation_interval_ms=100, workloads=[
                    WorkloadClass("small", runtime_ms=200, priority=50, request=1)]),
                WorkloadSet(count=100, creation_interval_ms=500, workloads=[
                    WorkloadClass("medium", runtime_ms=500, priority=100, request=5)]),
                WorkloadSet(count=50, creation_interval_ms=1200, workloads=[
                    WorkloadClass("large", runtime_ms=1000, priority=200, request=20)]),
            ])])]


@dataclass
class Arrival:
    at_s: float
    namespace: str
    name: str
    queue_name: str
    class_name: str
    priority: int
    request: int
    runtime_s: float


@dataclass
class GeneratedLoad:
    flavors: list = field(default_factory=list)
    cluster_queues: list = field(default_factory=list)
    local_queues: list = field(default_factory=list)
    namespaces: list = field(default_factory=list)
    arrivals: list = field(default_factory=list)  # sorted by at_s
    cq_class: dict = field(default_factory=dict)  # cq name -> class name


def generate(config: list, scale: float = 1.0) -> GeneratedLoad:
    """Expand the class spec. `scale` multiplies workload counts (the
    harness's knob for the 50k-pending scenarios)."""
    load = GeneratedLoad()
    rf = api.ResourceFlavor(metadata=ObjectMeta(name=FLAVOR))
    load.flavors.append(rf)

    for cohort_class in config:
        for ci in range(cohort_class.count):
            cohort_name = f"{cohort_class.class_name}-{ci}"
            for queue_class in cohort_class.queues_sets:
                for qi in range(queue_class.count):
                    cq_name = f"{cohort_name}-{queue_class.class_name}-{qi}"
                    namespace = cq_name
                    cq = api.ClusterQueue(metadata=ObjectMeta(name=cq_name))
                    cq.spec.cohort = cohort_name
                    cq.spec.namespace_selector = api.LabelSelector()
                    cq.spec.preemption = api.ClusterQueuePreemption(
                        reclaim_within_cohort=queue_class.reclaim_within_cohort,
                        within_cluster_queue=queue_class.within_cluster_queue)
                    cq.spec.resource_groups = [api.ResourceGroup(
                        covered_resources=[RESOURCE],
                        flavors=[api.FlavorQuotas(name=FLAVOR, resources=[
                            api.ResourceQuota(
                                name=RESOURCE,
                                nominal_quota=queue_class.nominal_quota,
                                borrowing_limit=queue_class.borrowing_limit)])])]
                    load.cluster_queues.append(cq)
                    load.cq_class[cq_name] = queue_class.class_name
                    lq = api.LocalQueue(metadata=ObjectMeta(
                        name="queue", namespace=namespace))
                    lq.spec.cluster_queue = cq_name
                    load.local_queues.append(lq)
                    load.namespaces.append(namespace)
                    for si, wl_set in enumerate(queue_class.workloads_sets):
                        count = max(1, int(wl_set.count * scale))
                        for wi in range(count):
                            wl_class = wl_set.workloads[wi % len(wl_set.workloads)]
                            load.arrivals.append(Arrival(
                                at_s=wi * wl_set.creation_interval_ms / 1000.0,
                                namespace=namespace,
                                name=f"{wl_class.class_name}-{si}-{wi}",
                                queue_name="queue",
                                class_name=wl_class.class_name,
                                priority=wl_class.priority,
                                request=wl_class.request,
                                runtime_s=wl_class.runtime_ms / 1000.0))
    load.arrivals.sort(key=lambda a: a.at_s)
    return load
