"""Workload/queue generator for the perf harness.

Equivalent of the reference's test/performance/scheduler/generator
driven by default_generator_config.yaml:1-28: a class spec tree
(cohorts x queue sets x workload sets) expands into ResourceFlavor/
ClusterQueue/LocalQueue objects plus a time-ordered arrival schedule.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import ObjectMeta

RESOURCE = "cpu"  # abstract units (the reference uses 1-unit requests)
FLAVOR = "default"


@dataclass
class WorkloadClass:
    class_name: str
    runtime_ms: int
    priority: int
    request: int


@dataclass
class WorkloadSet:
    count: int
    creation_interval_ms: int
    workloads: list = field(default_factory=list)  # list[WorkloadClass]


@dataclass
class QueueClass:
    class_name: str
    count: int
    nominal_quota: int
    borrowing_limit: Optional[int] = None
    reclaim_within_cohort: str = api.PREEMPTION_ANY
    within_cluster_queue: str = api.PREEMPTION_LOWER_PRIORITY
    workloads_sets: list = field(default_factory=list)  # list[WorkloadSet]


@dataclass
class CohortClass:
    class_name: str
    count: int
    queues_sets: list = field(default_factory=list)  # list[QueueClass]


def default_generator_config() -> list:
    """The reference's default config: 5 cohorts x 6 CQs, per CQ
    350 small + 100 medium + 50 large => 15,000 workloads / 30 CQs
    (default_generator_config.yaml:1-28)."""
    return [CohortClass(class_name="cohort", count=5, queues_sets=[
        QueueClass(
            class_name="cq", count=6, nominal_quota=20, borrowing_limit=100,
            workloads_sets=[
                WorkloadSet(count=350, creation_interval_ms=100, workloads=[
                    WorkloadClass("small", runtime_ms=200, priority=50, request=1)]),
                WorkloadSet(count=100, creation_interval_ms=500, workloads=[
                    WorkloadClass("medium", runtime_ms=500, priority=100, request=5)]),
                WorkloadSet(count=50, creation_interval_ms=1200, workloads=[
                    WorkloadClass("large", runtime_ms=1000, priority=200, request=20)]),
            ])])]


def north_star_generator_config() -> list:
    """BASELINE.json config #5 scale: 50,000 PENDING workloads across
    2,000 ClusterQueues (250 cohorts x 8 CQs); combine with
    generate(num_flavors=32) for the 32-ResourceFlavor axis.

    Quotas are sized the way the reference's harness sizes them
    (default_generator_config.yaml:1-28: only a fraction of standing
    demand fits at once): every workload arrives in a burst at t~0, per
    CQ the 16-flavor window carries 1 unit of quota per flavor (16 units
    of capacity) against 36 units of demand (18 small x1 + 5 medium x2 +
    2 large x4), so a STANDING backlog of tens of thousands drains only
    as completions free capacity — class time-to-admission and CQ usage
    are non-zero and priority-ordered, and admissions assign at real
    flavor-list depth (quota per flavor is one small workload, so the
    sequential assigner walks deep while the batched solve stays flat)."""
    return [CohortClass(class_name="cohort", count=250, queues_sets=[
        QueueClass(
            class_name="cq", count=8, nominal_quota=1, borrowing_limit=8,
            workloads_sets=[
                WorkloadSet(count=18, creation_interval_ms=2, workloads=[
                    WorkloadClass("small", runtime_ms=200, priority=50, request=1)]),
                WorkloadSet(count=5, creation_interval_ms=2, workloads=[
                    WorkloadClass("medium", runtime_ms=500, priority=100, request=2)]),
                WorkloadSet(count=2, creation_interval_ms=2, workloads=[
                    WorkloadClass("large", runtime_ms=1000, priority=200, request=4)]),
            ])])]


@dataclass
class Arrival:
    at_s: float
    namespace: str
    name: str
    queue_name: str
    class_name: str
    priority: int
    request: int
    runtime_s: float


@dataclass
class GeneratedLoad:
    flavors: list = field(default_factory=list)
    cluster_queues: list = field(default_factory=list)
    local_queues: list = field(default_factory=list)
    namespaces: list = field(default_factory=list)
    arrivals: list = field(default_factory=list)  # sorted by at_s
    cq_class: dict = field(default_factory=dict)  # cq name -> class name


def generate(config: list, scale: float = 1.0,
             num_flavors: int = 1) -> GeneratedLoad:
    """Expand the class spec. `scale` multiplies workload counts;
    `num_flavors` gives every CQ an ordered list of that many
    ResourceFlavors, each carrying the class's full quota (the
    32-flavor axis of the north-star shape)."""
    load = GeneratedLoad()
    flavor_names = ([FLAVOR] if num_flavors <= 1
                    else [f"{FLAVOR}-{i}" for i in range(num_flavors)])
    for fname in flavor_names:
        load.flavors.append(
            api.ResourceFlavor(metadata=ObjectMeta(name=fname)))
    # A resource group holds at most 16 flavors (CRD validation,
    # clusterqueue_types.go); with more system-wide flavors each CQ gets a
    # rotating 16-flavor window so all flavors stay in play.
    window = min(len(flavor_names), 16)
    cq_ordinal = 0

    for cohort_class in config:
        for ci in range(cohort_class.count):
            cohort_name = f"{cohort_class.class_name}-{ci}"
            for queue_class in cohort_class.queues_sets:
                for qi in range(queue_class.count):
                    cq_name = f"{cohort_name}-{queue_class.class_name}-{qi}"
                    namespace = cq_name
                    cq = api.ClusterQueue(metadata=ObjectMeta(name=cq_name))
                    cq.spec.cohort = cohort_name
                    cq.spec.namespace_selector = api.LabelSelector()
                    cq.spec.preemption = api.ClusterQueuePreemption(
                        reclaim_within_cohort=queue_class.reclaim_within_cohort,
                        within_cluster_queue=queue_class.within_cluster_queue)
                    start = (cq_ordinal * window) % len(flavor_names)
                    cq_flavors = [flavor_names[(start + k) % len(flavor_names)]
                                  for k in range(window)]
                    cq_ordinal += 1
                    cq.spec.resource_groups = [api.ResourceGroup(
                        covered_resources=[RESOURCE],
                        flavors=[api.FlavorQuotas(name=fname, resources=[
                            api.ResourceQuota(
                                name=RESOURCE,
                                nominal_quota=queue_class.nominal_quota,
                                borrowing_limit=queue_class.borrowing_limit)])
                            for fname in cq_flavors])]
                    load.cluster_queues.append(cq)
                    load.cq_class[cq_name] = queue_class.class_name
                    lq = api.LocalQueue(metadata=ObjectMeta(
                        name="queue", namespace=namespace))
                    lq.spec.cluster_queue = cq_name
                    load.local_queues.append(lq)
                    load.namespaces.append(namespace)
                    for si, wl_set in enumerate(queue_class.workloads_sets):
                        count = max(1, int(wl_set.count * scale))
                        for wi in range(count):
                            wl_class = wl_set.workloads[wi % len(wl_set.workloads)]
                            load.arrivals.append(Arrival(
                                at_s=wi * wl_set.creation_interval_ms / 1000.0,
                                namespace=namespace,
                                name=f"{wl_class.class_name}-{si}-{wi}",
                                queue_name="queue",
                                class_name=wl_class.class_name,
                                priority=wl_class.priority,
                                request=wl_class.request,
                                runtime_s=wl_class.runtime_ms / 1000.0))
    load.arrivals.sort(key=lambda a: a.at_s)
    return load
