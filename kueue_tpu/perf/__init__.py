"""Scheduler scalability harness.

Equivalent of the reference's test/performance/scheduler
(runner/generator/recorder/checker + minimalkueue): generate cohorts,
ClusterQueues and timed workload arrivals from a class spec, fake
workload execution on a virtual clock, record per-class time-to-admission
statistics, and check them against a rangespec.
"""

from kueue_tpu.perf.generator import (
    CohortClass,
    QueueClass,
    WorkloadClass,
    WorkloadSet,
    default_generator_config,
    generate,
    north_star_generator_config,
)
from kueue_tpu.perf.runner import RunResult, Runner
from kueue_tpu.perf.checker import (RangeSpec, SLOSpec, check, check_slo,
                                    default_rangespec,
                                    north_star_rangespec,
                                    refuse_cross_backend)

__all__ = [
    "CohortClass", "QueueClass", "WorkloadClass", "WorkloadSet",
    "default_generator_config", "generate",
    "Runner", "RunResult", "RangeSpec", "SLOSpec", "check", "check_slo",
    "default_rangespec", "north_star_rangespec", "refuse_cross_backend",
]
