"""kubeflow.org training CRDs — the shared shape the integrations consume
(reference: pkg/controller/jobs/kubeflow/kubeflowjob + per-kind wrappers).

All training-operator kinds share ReplicaSpecs + RunPolicy.Suspend;
each kind differs only in its replica-type names and which one leads
the PodSet order (master/launcher first).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.corev1 import PodTemplateSpec
from kueue_tpu.api.meta import ObjectMeta

JOB_SUCCEEDED = "Succeeded"
JOB_FAILED = "Failed"


@dataclass
class ReplicaSpec:
    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class RunPolicy:
    suspend: bool = False


@dataclass
class ReplicaStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class KFJobSpec:
    # replica type (e.g. "Master", "Worker") -> ReplicaSpec
    replica_specs: dict = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)


@dataclass
class KFJobStatus:
    conditions: list = field(default_factory=list)
    replica_statuses: dict = field(default_factory=dict)  # type -> ReplicaStatus


def _kf_kind(kind: str):
    @dataclass
    class _KFJob:
        metadata: ObjectMeta = field(default_factory=ObjectMeta)
        spec: KFJobSpec = field(default_factory=KFJobSpec)
        status: KFJobStatus = field(default_factory=KFJobStatus)

    _KFJob.__name__ = kind
    _KFJob.__qualname__ = kind
    _KFJob.KIND = kind
    return _KFJob


TFJob = _kf_kind("TFJob")
PyTorchJob = _kf_kind("PyTorchJob")
PaddleJob = _kf_kind("PaddleJob")
XGBoostJob = _kf_kind("XGBoostJob")
MXJob = _kf_kind("MXJob")
MPIJob = _kf_kind("MPIJob")

# replica-type orderings: the lead replica (master/launcher/server) comes
# first in the PodSet list (reference: kubeflowjob OrderedReplicaTypes)
REPLICA_ORDER = {
    "TFJob": ["Chief", "Master", "PS", "Worker"],
    "PyTorchJob": ["Master", "Worker"],
    "PaddleJob": ["Master", "Worker"],
    "XGBoostJob": ["Master", "Worker"],
    "MXJob": ["Scheduler", "Server", "Worker"],
    "MPIJob": ["Launcher", "Worker"],
}
