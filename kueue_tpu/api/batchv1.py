"""batch/v1 Job — the subset the job integration consumes
(reference: k8s batch/v1 as used by pkg/controller/jobs/job)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.corev1 import PodTemplateSpec
from kueue_tpu.api.meta import Condition, ObjectMeta

JOB_COMPLETE = "Complete"
JOB_FAILED = "Failed"


@dataclass
class JobSpec:
    parallelism: int = 1
    completions: Optional[int] = None
    suspend: bool = False
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class JobStatus:
    active: int = 0
    ready: int = 0
    succeeded: int = 0
    failed: int = 0
    conditions: list = field(default_factory=list)  # list[Condition]


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    KIND = "Job"
