"""Subset of k8s core/v1 pod types that the reference framework consumes.

The scheduler never runs pods; it only needs requests/limits, node
selectors/affinity, tolerations and scheduling gates — the inputs of
flavor assignment (reference: pkg/scheduler/flavorassigner) and the
fields the job integrations inject/restore (reference: pkg/podset).

Resource quantities are represented canonically as integers:
- "cpu": milli-CPU (reference: resources.Requests uses MilliValue for cpu,
  /root/reference/pkg/resources/requests.go:69)
- everything else: raw scalar value (bytes for memory, count for pods/GPUs).
Strings like "500m" / "2Gi" are accepted and parsed by `parse_quantity`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union

RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"

_SUFFIXES = {
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}


def parse_quantity(value: Union[str, int, float], resource: str = "") -> int:
    """Parse a k8s-style quantity into the canonical integer unit.

    For cpu the canonical unit is milli ("1" -> 1000, "500m" -> 500);
    for all other resources it is the scalar value ("2Gi" -> 2147483648).
    """
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        scalar = float(value)
        return round(scalar * 1000) if resource == RESOURCE_CPU else round(scalar)
    s = str(value).strip()
    if not s:
        return 0
    if s.endswith("m"):
        milli = float(s[:-1])
        if resource == RESOURCE_CPU:
            return round(milli)
        return round(milli / 1000)
    for suffix, mult in sorted(_SUFFIXES.items(), key=lambda kv: -len(kv[0])):
        if s.endswith(suffix):
            scalar = float(s[: -len(suffix)]) * mult
            return round(scalar * 1000) if resource == RESOURCE_CPU else round(scalar)
    scalar = float(s)
    return round(scalar * 1000) if resource == RESOURCE_CPU else round(scalar)


def format_quantity(value: int, resource: str) -> str:
    if resource == RESOURCE_CPU:
        if value % 1000 == 0:
            return str(value // 1000)
        return f"{value}m"
    return str(value)


ResourceList = dict[str, int]  # resource name -> canonical integer quantity


def parse_resource_list(raw: dict[str, Union[str, int, float]]) -> ResourceList:
    return {name: parse_quantity(v, name) for name, v in raw.items()}


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


def find_untolerated_taint(taints: list[Taint], tolerations: list[Toleration]) -> Optional[Taint]:
    """FindMatchingUntoleratedTaint over NoSchedule/NoExecute taints
    (reference: flavorassigner.go:440-445)."""
    for taint in taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(tol.tolerates(taint) for tol in tolerations):
            return taint
    return None


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: list[str] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        val = labels.get(self.key)
        if self.operator == "In":
            return val is not None and val in self.values
        if self.operator == "NotIn":
            return val is None or val not in self.values
        if self.operator == "Exists":
            return self.key in labels
        if self.operator == "DoesNotExist":
            return self.key not in labels
        if self.operator == "Gt":
            return val is not None and val.lstrip("-").isdigit() and int(val) > int(self.values[0])
        if self.operator == "Lt":
            return val is not None and val.lstrip("-").isdigit() and int(val) < int(self.values[0])
        raise ValueError(f"unknown node selector operator {self.operator}")


@dataclass
class NodeSelectorTerm:
    match_expressions: list[NodeSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        return all(e.matches(labels) for e in self.match_expressions)


@dataclass
class NodeSelector:
    # Terms are ORed.
    node_selector_terms: list[NodeSelectorTerm] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        if not self.node_selector_terms:
            return True
        return any(t.matches(labels) for t in self.node_selector_terms)


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None


@dataclass
class Container:
    name: str = ""
    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    affinity: Optional[Affinity] = None
    priority_class_name: str = ""
    priority: Optional[int] = None
    scheduling_gates: list[str] = field(default_factory=list)
    restart_policy: str = "Never"
    overhead: ResourceList = field(default_factory=dict)


@dataclass
class PodTemplateSpec:
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    spec: PodSpec = field(default_factory=PodSpec)


POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    conditions: list = field(default_factory=list)


@dataclass
class Pod:
    """corev1.Pod — enough for the plain-pod integration
    (reference: pkg/controller/jobs/pod)."""
    metadata: "ObjectMeta" = None
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"

    def __post_init__(self):
        if self.metadata is None:
            from kueue_tpu.api.meta import ObjectMeta
            self.metadata = ObjectMeta()


@dataclass
class Namespace:
    """corev1.Namespace — only labels matter (CQ namespaceSelector,
    reference: scheduler.go:421-425)."""
    metadata: "ObjectMeta" = None

    KIND = "Namespace"

    def __post_init__(self):
        if self.metadata is None:
            from kueue_tpu.api.meta import ObjectMeta
            self.metadata = ObjectMeta()
