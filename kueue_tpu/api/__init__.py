"""API types for kueue_tpu.

Python-native equivalents of the reference CRD Go structs
(/root/reference/apis/kueue/v1beta1, apis/kueue/v1alpha1,
apis/config/v1beta1). These are plain dataclasses; objects live in the
in-process object store (`kueue_tpu.sim`) instead of etcd.
"""

from kueue_tpu.api.meta import (  # noqa: F401
    Condition,
    LabelSelector,
    LabelSelectorRequirement,
    ObjectMeta,
    OwnerReference,
    find_condition,
    is_condition_true,
    set_condition,
)
from kueue_tpu.api.corev1 import (  # noqa: F401
    Affinity,
    Container,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PodSpec,
    PodTemplateSpec,
    Taint,
    Toleration,
)
from kueue_tpu.api.kueue import (  # noqa: F401
    Admission,
    AdmissionCheck,
    AdmissionCheckSpec,
    AdmissionCheckState,
    AdmissionCheckStrategyRule,
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    ClusterQueueSpec,
    ClusterQueueStatus,
    Cohort,
    CohortSpec,
    FairSharing,
    FlavorFungibility,
    FlavorQuotas,
    FlavorUsage,
    LocalQueue,
    LocalQueueSpec,
    LocalQueueStatus,
    PodSet,
    PodSetAssignment,
    PodSetUpdate,
    ReclaimablePod,
    RequeueState,
    ResourceFlavor,
    ResourceFlavorSpec,
    ResourceGroup,
    ResourceQuota,
    ResourceUsage,
    Workload,
    WorkloadPriorityClass,
    WorkloadSpec,
    WorkloadStatus,
)
