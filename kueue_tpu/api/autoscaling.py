"""cluster-autoscaler ProvisioningRequest + kueue config CRDs.

Reference: apis/kueue/v1beta1/provisioningrequestconfig_types.go:25-80 and
the autoscaler.x-k8s.io/v1beta1 ProvisioningRequest consumed by
pkg/controller/admissionchecks/provisioning.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.corev1 import PodTemplateSpec
from kueue_tpu.api.meta import ObjectMeta

PROVISIONED = "Provisioned"
FAILED = "Failed"
ACCEPTED = "Accepted"
BOOKING_EXPIRED = "BookingExpired"
CAPACITY_REVOKED = "CapacityRevoked"


@dataclass
class ProvisioningRequestPodSet:
    pod_template_ref: str = ""
    count: int = 0


@dataclass
class ProvisioningRequestSpec:
    provisioning_class_name: str = ""
    pod_sets: list = field(default_factory=list)
    parameters: dict = field(default_factory=dict)


@dataclass
class ProvisioningRequestStatus:
    conditions: list = field(default_factory=list)
    provisioning_class_details: dict = field(default_factory=dict)


@dataclass
class ProvisioningRequest:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ProvisioningRequestSpec = field(default_factory=ProvisioningRequestSpec)
    status: ProvisioningRequestStatus = field(default_factory=ProvisioningRequestStatus)

    KIND = "ProvisioningRequest"


@dataclass
class ProvisioningRequestConfigSpec:
    provisioning_class_name: str = ""
    parameters: dict = field(default_factory=dict)
    # resources that gate podset inclusion; empty = all podsets
    managed_resources: list = field(default_factory=list)


@dataclass
class ProvisioningRequestConfig:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ProvisioningRequestConfigSpec = field(
        default_factory=ProvisioningRequestConfigSpec)

    KIND = "ProvisioningRequestConfig"


@dataclass
class PodTemplate:
    """corev1.PodTemplate object created alongside a ProvisioningRequest."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)

    KIND = "PodTemplate"


# --- MultiKueue CRDs (reference: apis/kueue/v1alpha1/multikueue_types.go) ---


@dataclass
class MultiKueueClusterSpec:
    # the reference holds a kubeconfig secret/path; the sim resolves the
    # cluster name through an injected registry of remote stores
    kubeconfig_ref: str = ""


@dataclass
class MultiKueueClusterStatus:
    conditions: list = field(default_factory=list)


@dataclass
class MultiKueueCluster:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiKueueClusterSpec = field(default_factory=MultiKueueClusterSpec)
    status: MultiKueueClusterStatus = field(default_factory=MultiKueueClusterStatus)

    KIND = "MultiKueueCluster"


@dataclass
class MultiKueueConfigSpec:
    clusters: list = field(default_factory=list)  # MultiKueueCluster names


@dataclass
class MultiKueueConfig:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiKueueConfigSpec = field(default_factory=MultiKueueConfigSpec)

    KIND = "MultiKueueConfig"
