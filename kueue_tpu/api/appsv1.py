"""apps/v1 Deployment — the subset the serving integration consumes
(reference: pkg/controller/jobs/deployment)."""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_tpu.api.corev1 import PodTemplateSpec
from kueue_tpu.api.meta import ObjectMeta


@dataclass
class DeploymentSpec:
    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DeploymentStatus:
    ready_replicas: int = 0
    available_replicas: int = 0


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    KIND = "Deployment"
