"""Object metadata, conditions and label selectors.

Equivalents of the k8s apimachinery types the reference relies on:
metav1.ObjectMeta, metav1.Condition (+ apimeta condition helpers) and
metav1.LabelSelector. Timestamps are float unix seconds.
"""

from __future__ import annotations

import fnmatch
import itertools
import time as _time
from dataclasses import dataclass, field
from typing import Optional

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    return f"{prefix}-{next(_uid_counter)}"


class Clock:
    """Injectable time source (reference uses k8s.io/utils/clock)."""

    def now(self) -> float:
        return _time.time()


class FakeClock(Clock):
    def __init__(self, t: float = 1000.0):
        self.t = t

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


REAL_CLOCK = Clock()


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    generation: int = 1
    resource_version: int = 0
    # None = unset (the sim store stamps clock.now() on create);
    # 0.0 is a valid explicit timestamp
    creation_timestamp: Optional[float] = None
    deletion_timestamp: Optional[float] = None
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    owner_references: list[OwnerReference] = field(default_factory=list)


@dataclass
class Condition:
    type: str = ""
    status: str = "False"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0
    observed_generation: int = 0


def find_condition(conditions: list[Condition], ctype: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def is_condition_true(conditions: list[Condition], ctype: str) -> bool:
    c = find_condition(conditions, ctype)
    return c is not None and c.status == "True"


def is_condition_false(conditions: list[Condition], ctype: str) -> bool:
    c = find_condition(conditions, ctype)
    return c is not None and c.status == "False"


def set_condition(conditions: list[Condition], new: Condition, now: Optional[float] = None) -> bool:
    """apimeta.SetStatusCondition: last_transition_time only moves when status flips.

    Returns True if anything changed.
    """
    if now is None:
        now = _time.time()
    existing = find_condition(conditions, new.type)
    if existing is None:
        if new.last_transition_time == 0.0:
            new.last_transition_time = now
        conditions.append(new)
        return True
    changed = False
    if existing.status != new.status:
        existing.status = new.status
        existing.last_transition_time = new.last_transition_time or now
        changed = True
    if existing.reason != new.reason:
        existing.reason = new.reason
        changed = True
    if existing.message != new.message:
        existing.message = new.message
        changed = True
    if existing.observed_generation != new.observed_generation:
        existing.observed_generation = new.observed_generation
        changed = True
    return changed


def remove_condition(conditions: list[Condition], ctype: str) -> None:
    conditions[:] = [c for c in conditions if c.type != ctype]


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: list[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    """metav1.LabelSelector. An empty selector matches everything; None matches nothing
    (matching the semantics of LabelSelectorAsSelector)."""

    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            val = labels.get(req.key)
            if req.operator == "In":
                if val is None or val not in req.values:
                    return False
            elif req.operator == "NotIn":
                if val is not None and val in req.values:
                    return False
            elif req.operator == "Exists":
                if req.key not in labels:
                    return False
            elif req.operator == "DoesNotExist":
                if req.key in labels:
                    return False
            else:
                raise ValueError(f"unknown selector operator {req.operator}")
        return True


def match_glob(pattern: str, value: str) -> bool:
    return fnmatch.fnmatchcase(value, pattern)
