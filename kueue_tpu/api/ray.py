"""ray.io CRDs — the subset the integrations consume
(reference: pkg/controller/jobs/rayjob, raycluster)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.corev1 import PodTemplateSpec
from kueue_tpu.api.meta import ObjectMeta

RAYJOB_COMPLETE = "Complete"
RAYJOB_FAILED = "Failed"


@dataclass
class HeadGroupSpec:
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class WorkerGroupSpec:
    group_name: str = ""
    replicas: int = 1
    min_replicas: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class RayClusterSpec:
    head_group_spec: HeadGroupSpec = field(default_factory=HeadGroupSpec)
    worker_group_specs: list = field(default_factory=list)
    suspend: bool = False


@dataclass
class RayJobSpec:
    ray_cluster_spec: RayClusterSpec = field(default_factory=RayClusterSpec)
    suspend: bool = False


@dataclass
class RayJobStatus:
    job_status: str = ""           # "" | RUNNING | SUCCEEDED | FAILED
    job_deployment_status: str = ""
    ready_worker_replicas: int = 0
    message: str = ""


@dataclass
class RayClusterStatus:
    ready_worker_replicas: int = 0
    available_worker_replicas: int = 0
    state: str = ""
    conditions: list = field(default_factory=list)


@dataclass
class RayJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: RayJobSpec = field(default_factory=RayJobSpec)
    status: RayJobStatus = field(default_factory=RayJobStatus)

    KIND = "RayJob"


@dataclass
class RayCluster:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: RayClusterSpec = field(default_factory=RayClusterSpec)
    status: RayClusterStatus = field(default_factory=RayClusterStatus)

    KIND = "RayCluster"
