"""jobset.x-k8s.io/v1alpha2 JobSet — the subset the integration consumes
(reference: pkg/controller/jobs/jobset)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.batchv1 import JobSpec
from kueue_tpu.api.meta import ObjectMeta


@dataclass
class ReplicatedJob:
    name: str = ""
    replicas: int = 1
    template: JobSpec = field(default_factory=JobSpec)


@dataclass
class JobSetSpec:
    replicated_jobs: list = field(default_factory=list)  # list[ReplicatedJob]
    suspend: bool = False


@dataclass
class ReplicatedJobStatus:
    name: str = ""
    ready: int = 0
    succeeded: int = 0
    active: int = 0


@dataclass
class JobSetStatus:
    conditions: list = field(default_factory=list)
    replicated_jobs_status: list = field(default_factory=list)


@dataclass
class JobSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSetSpec = field(default_factory=JobSetSpec)
    status: JobSetStatus = field(default_factory=JobSetStatus)

    KIND = "JobSet"


JOBSET_COMPLETED = "Completed"
JOBSET_FAILED = "Failed"
