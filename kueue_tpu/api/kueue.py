"""Core kueue_tpu API types.

Equivalents of the reference CRDs:
- Workload / Admission / PodSetAssignment: apis/kueue/v1beta1/workload_types.go
- ClusterQueue / ResourceGroup / quotas / preemption / flavorFungibility:
  apis/kueue/v1beta1/clusterqueue_types.go
- LocalQueue: apis/kueue/v1beta1/localqueue_types.go
- ResourceFlavor: apis/kueue/v1beta1/resourceflavor_types.go
- AdmissionCheck: apis/kueue/v1beta1/admissioncheck_types.go
- WorkloadPriorityClass: apis/kueue/v1beta1/workloadpriorityclass_types.go
- Cohort (hierarchical): apis/kueue/v1alpha1/cohort_types.go
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.corev1 import PodTemplateSpec, ResourceList, Taint, Toleration
from kueue_tpu.api.meta import Condition, LabelSelector, ObjectMeta

# --- constants (reference: apis/kueue/v1beta1/workload_types.go:295-434,
#     pkg/constants) ---

QUEUE_LABEL = "kueue.x-k8s.io/queue-name"
PRIORITY_CLASS_LABEL = "kueue.x-k8s.io/priority-class"
PREBUILT_WORKLOAD_LABEL = "kueue.x-k8s.io/prebuilt-workload-name"
MANAGED_LABEL = "kueue.x-k8s.io/managed"
ADMISSION_GATE = "kueue.x-k8s.io/admission"
RESOURCE_IN_USE_FINALIZER = "kueue.x-k8s.io/resource-in-use"
DEFAULT_PODSET_NAME = "main"
WORKLOAD_PRIORITY_CLASS_SOURCE = "kueue.x-k8s.io/workloadpriorityclass"
POD_PRIORITY_CLASS_SOURCE = "scheduling.k8s.io/priorityclass"

# Workload condition types
WORKLOAD_QUOTA_RESERVED = "QuotaReserved"
WORKLOAD_ADMITTED = "Admitted"
WORKLOAD_FINISHED = "Finished"
WORKLOAD_PODS_READY = "PodsReady"
WORKLOAD_EVICTED = "Evicted"
WORKLOAD_PREEMPTED = "Preempted"
WORKLOAD_REQUEUED = "Requeued"
WORKLOAD_DEACTIVATION_TARGET = "DeactivationTarget"

# Eviction reasons
EVICTED_BY_PREEMPTION = "Preempted"
EVICTED_BY_PODS_READY_TIMEOUT = "PodsReadyTimeout"
EVICTED_BY_ADMISSION_CHECK = "AdmissionCheck"
EVICTED_BY_CLUSTER_QUEUE_STOPPED = "ClusterQueueStopped"
EVICTED_BY_LOCAL_QUEUE_STOPPED = "LocalQueueStopped"
EVICTED_BY_DEACTIVATION = "InactiveWorkload"

# Preemption reasons (reference: workload_types.go, preemption.go:187-192)
IN_CLUSTER_QUEUE_REASON = "InClusterQueue"
IN_COHORT_RECLAMATION_REASON = "InCohortReclamation"
IN_COHORT_FAIR_SHARING_REASON = "InCohortFairSharing"
IN_COHORT_RECLAIM_WHILE_BORROWING_REASON = "InCohortReclaimWhileBorrowing"

# ClusterQueue condition
CLUSTER_QUEUE_ACTIVE = "Active"
LOCAL_QUEUE_ACTIVE = "Active"

# Queueing strategies
STRICT_FIFO = "StrictFIFO"
BEST_EFFORT_FIFO = "BestEffortFIFO"

# Preemption policies
PREEMPTION_NEVER = "Never"
PREEMPTION_LOWER_PRIORITY = "LowerPriority"
PREEMPTION_LOWER_OR_NEWER_EQUAL_PRIORITY = "LowerOrNewerEqualPriority"
PREEMPTION_ANY = "Any"

# BorrowWithinCohort policies
BORROW_WITHIN_COHORT_NEVER = "Never"
BORROW_WITHIN_COHORT_LOWER_PRIORITY = "LowerPriority"

# FlavorFungibility policies
TRY_NEXT_FLAVOR = "TryNextFlavor"
BORROW = "Borrow"
PREEMPT = "Preempt"

# StopPolicy
STOP_POLICY_NONE = "None"
HOLD = "Hold"
HOLD_AND_DRAIN = "HoldAndDrain"

# AdmissionCheck states (reference: admissioncheck_types.go)
CHECK_STATE_RETRY = "Retry"
CHECK_STATE_REJECTED = "Rejected"
CHECK_STATE_READY = "Ready"
CHECK_STATE_PENDING = "Pending"

# AdmissionCheck condition
ADMISSION_CHECK_ACTIVE = "Active"

# Requeued condition reasons (reference: workload_types.go:380-410,
# pkg/controller/core/workload_controller.go:160-200)
WORKLOAD_REACTIVATED = "Reactivated"
WORKLOAD_BACKOFF_FINISHED = "BackoffFinished"
WORKLOAD_LOCAL_QUEUE_RESTARTED = "LocalQueueRestarted"
WORKLOAD_CLUSTER_QUEUE_RESTARTED = "ClusterQueueRestarted"
WORKLOAD_REQUEUING_LIMIT_EXCEEDED = "RequeuingLimitExceeded"

# Workload inadmissible reason (workload_controller.go:285-330)
WORKLOAD_INADMISSIBLE = "Inadmissible"


# --- Workload (reference: workload_types.go:26-293) ---

@dataclass
class PodSet:
    name: str = DEFAULT_PODSET_NAME
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    count: int = 1
    min_count: Optional[int] = None  # enables partial admission when set


@dataclass
class PodSetAssignment:
    name: str = ""
    flavors: dict[str, str] = field(default_factory=dict)  # resource -> flavor name
    resource_usage: ResourceList = field(default_factory=dict)
    count: Optional[int] = None


@dataclass
class Admission:
    cluster_queue: str = ""
    pod_set_assignments: list[PodSetAssignment] = field(default_factory=list)


@dataclass
class PodSetUpdate:
    """Admission-check-injected pod template tweaks
    (reference: workload_types.go:226-284)."""
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)


@dataclass
class AdmissionCheckState:
    name: str = ""
    state: str = CHECK_STATE_PENDING
    message: str = ""
    last_transition_time: float = 0.0
    pod_set_updates: list[PodSetUpdate] = field(default_factory=list)


@dataclass
class ReclaimablePod:
    name: str = ""
    count: int = 0


@dataclass
class RequeueState:
    count: int = 0
    requeue_at: Optional[float] = None


@dataclass
class WorkloadSpec:
    pod_sets: list[PodSet] = field(default_factory=list)
    queue_name: str = ""
    priority_class_name: str = ""
    priority: Optional[int] = None
    priority_class_source: str = ""
    active: bool = True


@dataclass
class WorkloadStatus:
    conditions: list[Condition] = field(default_factory=list)
    admission: Optional[Admission] = None
    requeue_state: Optional[RequeueState] = None
    reclaimable_pods: list[ReclaimablePod] = field(default_factory=list)
    admission_checks: list[AdmissionCheckState] = field(default_factory=list)


@dataclass
class Workload:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WorkloadSpec = field(default_factory=WorkloadSpec)
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    KIND = "Workload"


def _clone_meta(m: ObjectMeta) -> ObjectMeta:
    from dataclasses import replace as _r
    return ObjectMeta(
        name=m.name, namespace=m.namespace, uid=m.uid,
        generation=m.generation, resource_version=m.resource_version,
        creation_timestamp=m.creation_timestamp,
        deletion_timestamp=m.deletion_timestamp,
        labels=dict(m.labels), annotations=dict(m.annotations),
        finalizers=list(m.finalizers),
        owner_references=[_r(o) for o in m.owner_references])


def _clone_flavor_usage(lst: list) -> list:
    return [FlavorUsage(name=f.name,
                        resources=[ResourceUsage(name=r.name, total=r.total,
                                                 borrowed=r.borrowed)
                                   for r in f.resources])
            for f in lst]


def clone_cluster_queue(cq: "ClusterQueue") -> "ClusterQueue":
    """Hand-rolled deep copy (see clone_workload): ClusterQueues carry up
    to 16 FlavorQuotas x resources in spec plus the same again in status
    usage lists — generic deepcopy of one costs more than a whole
    scheduling decision at the 2k-CQ scale."""
    from dataclasses import replace as _r
    from kueue_tpu.api.meta import LabelSelector, LabelSelectorRequirement
    s = cq.spec
    sel = s.namespace_selector
    if sel is not None:
        sel = LabelSelector(
            match_labels=dict(sel.match_labels),
            match_expressions=[LabelSelectorRequirement(
                key=e.key, operator=e.operator, values=list(e.values))
                for e in sel.match_expressions])
    pre = s.preemption
    pre = ClusterQueuePreemption(
        reclaim_within_cohort=pre.reclaim_within_cohort,
        borrow_within_cohort=(_r(pre.borrow_within_cohort)
                              if pre.borrow_within_cohort is not None
                              else None),
        within_cluster_queue=pre.within_cluster_queue)
    st = cq.status
    return ClusterQueue(
        metadata=_clone_meta(cq.metadata),
        spec=ClusterQueueSpec(
            resource_groups=[ResourceGroup(
                covered_resources=list(rg.covered_resources),
                flavors=[FlavorQuotas(name=fq.name,
                                      resources=[_r(q) for q in fq.resources])
                         for fq in rg.flavors])
                for rg in s.resource_groups],
            cohort=s.cohort,
            queueing_strategy=s.queueing_strategy,
            namespace_selector=sel,
            flavor_fungibility=_r(s.flavor_fungibility),
            preemption=pre,
            admission_checks=list(s.admission_checks),
            admission_checks_strategy=[
                AdmissionCheckStrategyRule(name=r.name,
                                           on_flavors=list(r.on_flavors))
                for r in s.admission_checks_strategy],
            fair_sharing=(_r(s.fair_sharing)
                          if s.fair_sharing is not None else None),
            stop_policy=s.stop_policy),
        status=ClusterQueueStatus(
            conditions=[_r(c) for c in st.conditions],
            flavors_reservation=_clone_flavor_usage(st.flavors_reservation),
            flavors_usage=_clone_flavor_usage(st.flavors_usage),
            pending_workloads=st.pending_workloads,
            reserving_workloads=st.reserving_workloads,
            admitted_workloads=st.admitted_workloads,
            fair_sharing_weighted_share=st.fair_sharing_weighted_share))


def clone_local_queue(lq: "LocalQueue") -> "LocalQueue":
    """Hand-rolled deep copy (see clone_workload)."""
    from dataclasses import replace as _r
    st = lq.status
    return LocalQueue(
        metadata=_clone_meta(lq.metadata),
        spec=LocalQueueSpec(cluster_queue=lq.spec.cluster_queue,
                            stop_policy=lq.spec.stop_policy),
        status=LocalQueueStatus(
            conditions=[_r(c) for c in st.conditions],
            pending_workloads=st.pending_workloads,
            reserving_workloads=st.reserving_workloads,
            admitted_workloads=st.admitted_workloads,
            flavors_reservation=_clone_flavor_usage(st.flavors_reservation),
            flavors_usage=_clone_flavor_usage(st.flavors_usage)))


def clone_workload(wl: Workload) -> Workload:
    """Hand-rolled deep copy of a Workload: semantically identical to
    copy.deepcopy but ~10x faster (no memo bookkeeping / reflection).
    Workloads are the store's hottest kind — every reconciler read and
    every status write copies one, which dominated the control-plane
    profile at the 50k-workload scale. Field lists mirror the dataclasses
    above; tests pin equality against copy.deepcopy."""
    from dataclasses import replace as _r
    from kueue_tpu.api.corev1 import (
        Affinity, Container, NodeAffinity, NodeSelector,
        NodeSelectorRequirement, NodeSelectorTerm, PodSpec, PodTemplateSpec)

    def clone_pod_spec(s):
        aff = s.affinity
        if aff is not None:
            na = aff.node_affinity
            if na is not None and na.required is not None:
                req = NodeSelector(node_selector_terms=[
                    NodeSelectorTerm(match_expressions=[
                        NodeSelectorRequirement(key=e.key, operator=e.operator,
                                                values=list(e.values))
                        for e in t.match_expressions])
                    for t in na.required.node_selector_terms])
                na = NodeAffinity(required=req)
            elif na is not None:
                na = NodeAffinity(required=None)
            aff = Affinity(node_affinity=na)
        return PodSpec(
            containers=[Container(name=c.name, requests=dict(c.requests),
                                  limits=dict(c.limits))
                        for c in s.containers],
            init_containers=[Container(name=c.name, requests=dict(c.requests),
                                       limits=dict(c.limits))
                             for c in s.init_containers],
            node_selector=dict(s.node_selector),
            tolerations=[_r(t) for t in s.tolerations],
            affinity=aff,
            priority_class_name=s.priority_class_name,
            priority=s.priority,
            scheduling_gates=list(s.scheduling_gates),
            restart_policy=s.restart_policy,
            overhead=dict(s.overhead))

    st = wl.status
    return Workload(
        metadata=_clone_meta(wl.metadata),
        spec=WorkloadSpec(
            pod_sets=[PodSet(name=ps.name,
                             template=PodTemplateSpec(
                                 labels=dict(ps.template.labels),
                                 annotations=dict(ps.template.annotations),
                                 spec=clone_pod_spec(ps.template.spec)),
                             count=ps.count, min_count=ps.min_count)
                      for ps in wl.spec.pod_sets],
            queue_name=wl.spec.queue_name,
            priority_class_name=wl.spec.priority_class_name,
            priority=wl.spec.priority,
            priority_class_source=wl.spec.priority_class_source,
            active=wl.spec.active),
        status=WorkloadStatus(
            conditions=[_r(c) for c in st.conditions],
            admission=(Admission(
                cluster_queue=st.admission.cluster_queue,
                pod_set_assignments=[
                    PodSetAssignment(name=a.name, flavors=dict(a.flavors),
                                     resource_usage=dict(a.resource_usage),
                                     count=a.count)
                    for a in st.admission.pod_set_assignments])
                if st.admission is not None else None),
            requeue_state=(_r(st.requeue_state)
                           if st.requeue_state is not None else None),
            reclaimable_pods=[_r(p) for p in st.reclaimable_pods],
            admission_checks=[AdmissionCheckState(
                name=s.name, state=s.state, message=s.message,
                last_transition_time=s.last_transition_time,
                pod_set_updates=[PodSetUpdate(
                    name=u.name, labels=dict(u.labels),
                    annotations=dict(u.annotations),
                    node_selector=dict(u.node_selector),
                    tolerations=[_r(t) for t in u.tolerations])
                    for u in s.pod_set_updates])
                for s in st.admission_checks]))


# --- ClusterQueue (reference: clusterqueue_types.go) ---

@dataclass
class ResourceQuota:
    name: str = ""  # resource name
    nominal_quota: int = 0
    borrowing_limit: Optional[int] = None
    lending_limit: Optional[int] = None


@dataclass
class FlavorQuotas:
    name: str = ""  # flavor name
    resources: list[ResourceQuota] = field(default_factory=list)


@dataclass
class ResourceGroup:
    covered_resources: list[str] = field(default_factory=list)
    flavors: list[FlavorQuotas] = field(default_factory=list)


@dataclass
class BorrowWithinCohort:
    policy: str = BORROW_WITHIN_COHORT_NEVER
    max_priority_threshold: Optional[int] = None


@dataclass
class ClusterQueuePreemption:
    reclaim_within_cohort: str = PREEMPTION_NEVER
    borrow_within_cohort: Optional[BorrowWithinCohort] = None
    within_cluster_queue: str = PREEMPTION_NEVER


@dataclass
class FlavorFungibility:
    when_can_borrow: str = BORROW
    when_can_preempt: str = TRY_NEXT_FLAVOR


@dataclass
class FairSharing:
    # weight in milli-units (reference stores resource.Quantity; 1000 == weight 1)
    weight: int = 1000


@dataclass
class AdmissionCheckStrategyRule:
    name: str = ""
    on_flavors: list[str] = field(default_factory=list)  # empty = all flavors


@dataclass
class ClusterQueueSpec:
    resource_groups: list[ResourceGroup] = field(default_factory=list)
    cohort: str = ""
    queueing_strategy: str = BEST_EFFORT_FIFO
    # None matches nothing; empty selector matches all namespaces.
    namespace_selector: Optional[LabelSelector] = None
    flavor_fungibility: FlavorFungibility = field(default_factory=FlavorFungibility)
    preemption: ClusterQueuePreemption = field(default_factory=ClusterQueuePreemption)
    admission_checks: list[str] = field(default_factory=list)
    admission_checks_strategy: list[AdmissionCheckStrategyRule] = field(default_factory=list)
    fair_sharing: Optional[FairSharing] = None
    stop_policy: str = STOP_POLICY_NONE


@dataclass
class ResourceUsage:
    name: str = ""
    total: int = 0
    borrowed: int = 0


@dataclass
class FlavorUsage:
    name: str = ""
    resources: list[ResourceUsage] = field(default_factory=list)


@dataclass
class ClusterQueueStatus:
    conditions: list[Condition] = field(default_factory=list)
    flavors_reservation: list[FlavorUsage] = field(default_factory=list)
    flavors_usage: list[FlavorUsage] = field(default_factory=list)
    pending_workloads: int = 0
    reserving_workloads: int = 0
    admitted_workloads: int = 0
    fair_sharing_weighted_share: int = 0


@dataclass
class ClusterQueue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ClusterQueueSpec = field(default_factory=ClusterQueueSpec)
    status: ClusterQueueStatus = field(default_factory=ClusterQueueStatus)

    KIND = "ClusterQueue"


# --- Cohort (reference: apis/kueue/v1alpha1/cohort_types.go) ---

@dataclass
class CohortSpec:
    parent: str = ""
    resource_groups: list[ResourceGroup] = field(default_factory=list)


@dataclass
class Cohort:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CohortSpec = field(default_factory=CohortSpec)

    KIND = "Cohort"


# --- LocalQueue (reference: localqueue_types.go) ---

@dataclass
class LocalQueueSpec:
    cluster_queue: str = ""
    stop_policy: str = STOP_POLICY_NONE


@dataclass
class LocalQueueStatus:
    conditions: list[Condition] = field(default_factory=list)
    pending_workloads: int = 0
    reserving_workloads: int = 0
    admitted_workloads: int = 0
    flavors_reservation: list[FlavorUsage] = field(default_factory=list)
    flavors_usage: list[FlavorUsage] = field(default_factory=list)


@dataclass
class LocalQueue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LocalQueueSpec = field(default_factory=LocalQueueSpec)
    status: LocalQueueStatus = field(default_factory=LocalQueueStatus)

    KIND = "LocalQueue"


# --- ResourceFlavor (reference: resourceflavor_types.go:39-90) ---

@dataclass
class ResourceFlavorSpec:
    node_labels: dict[str, str] = field(default_factory=dict)
    node_taints: list[Taint] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)


@dataclass
class ResourceFlavor:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceFlavorSpec = field(default_factory=ResourceFlavorSpec)

    KIND = "ResourceFlavor"


# --- AdmissionCheck (reference: admissioncheck_types.go:48-137) ---

@dataclass
class AdmissionCheckParametersReference:
    api_group: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class AdmissionCheckSpec:
    controller_name: str = ""
    parameters: Optional[AdmissionCheckParametersReference] = None


@dataclass
class AdmissionCheckStatus:
    conditions: list[Condition] = field(default_factory=list)


@dataclass
class AdmissionCheck:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: AdmissionCheckSpec = field(default_factory=AdmissionCheckSpec)
    status: AdmissionCheckStatus = field(default_factory=AdmissionCheckStatus)

    KIND = "AdmissionCheck"


# --- WorkloadPriorityClass (reference: workloadpriorityclass_types.go:31) ---

@dataclass
class WorkloadPriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    description: str = ""

    KIND = "WorkloadPriorityClass"


# k8s scheduling.k8s.io PriorityClass analogue (pod priority source)
@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    description: str = ""

    KIND = "PriorityClass"
