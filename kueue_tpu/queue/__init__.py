"""Pending-workload queues (reference: pkg/queue)."""

from kueue_tpu.queue.cluster_queue import ClusterQueueHeap, RequeueReason  # noqa: F401
from kueue_tpu.queue.manager import Manager  # noqa: F401
