"""Per-ClusterQueue pending heap with inadmissible-workload parking.

Equivalent of the reference's pkg/queue/cluster_queue.go: a
priority+timestamp heap, a separate inadmissibleWorkloads map with
requeue-backoff gating, popCycle/queueInadmissibleCycle race avoidance,
and strategy-dependent requeue (StrictFIFO requeues to the heap,
BestEffortFIFO parks inadmissible workloads until a relevant event).
"""

from __future__ import annotations

import threading
from enum import Enum
from typing import Callable, Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import Clock, is_condition_false
from kueue_tpu.core import priority as prioritypkg
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.utils.heap import Heap


class RequeueReason(Enum):
    GENERIC = ""
    FAILED_AFTER_NOMINATION = "FailedAfterNomination"
    NAMESPACE_MISMATCH = "NamespaceMismatch"
    PENDING_PREEMPTION = "PendingPreemption"


def queue_ordering_func(ordering: wlpkg.Ordering) -> Callable:
    """Priority desc, then queue-order timestamp asc
    (reference: cluster_queue.go:416-429)."""

    def less(a: wlpkg.Info, b: wlpkg.Info) -> bool:
        p1 = prioritypkg.priority(a.obj)
        p2 = prioritypkg.priority(b.obj)
        if p1 != p2:
            return p1 > p2
        return ordering.queue_order_timestamp(a.obj) <= ordering.queue_order_timestamp(b.obj)

    return less


class ClusterQueueHeap:
    def __init__(self, cq: api.ClusterQueue, ordering: wlpkg.Ordering, clock: Clock):
        self._less = queue_ordering_func(ordering)
        self.heap: Heap = Heap(key_func=lambda i: i.key, less_func=self._less)
        self.inadmissible: dict = {}  # key -> Info
        self.pop_cycle = 0
        self.queue_inadmissible_cycle = -1
        self.inflight: Optional[wlpkg.Info] = None
        self.clock = clock
        self._lock = threading.RLock()
        self.update(cq)

    def update(self, cq: api.ClusterQueue) -> None:
        with self._lock:
            self.name = cq.metadata.name
            self.queueing_strategy = cq.spec.queueing_strategy
            self.namespace_selector = cq.spec.namespace_selector
            self.cohort = cq.spec.cohort
            self.active = True  # refreshed by the manager from cache state

    # --- push/pop ---

    def push_or_update(self, info: wlpkg.Info) -> None:
        with self._lock:
            key = info.key
            self._forget_inflight(key)
            old = self.inadmissible.get(key)
            if old is not None:
                # Keep parked if nothing admission-relevant changed
                # (reference: cluster_queue.go:150-166).
                if self._equivalent_for_requeue(old.obj, info.obj):
                    self.inadmissible[key] = info
                    return
                del self.inadmissible[key]
            if self.heap.get_by_key(key) is None and not self.backoff_expired(info):
                self.inadmissible[key] = info
                return
            self.heap.push_or_update(info)

    @staticmethod
    def _equivalent_for_requeue(old: api.Workload, new: api.Workload) -> bool:
        from kueue_tpu.api.meta import find_condition
        return (old.spec == new.spec
                and old.status.reclaimable_pods == new.status.reclaimable_pods
                and find_condition(old.status.conditions, api.WORKLOAD_EVICTED)
                == find_condition(new.status.conditions, api.WORKLOAD_EVICTED)
                and find_condition(old.status.conditions, api.WORKLOAD_REQUEUED)
                == find_condition(new.status.conditions, api.WORKLOAD_REQUEUED))

    def backoff_expired(self, info: wlpkg.Info) -> bool:
        """reference: cluster_queue.go:176-190."""
        if is_condition_false(info.obj.status.conditions, api.WORKLOAD_REQUEUED):
            return False
        rs = info.obj.status.requeue_state
        if rs is None or rs.requeue_at is None:
            return True
        if wlpkg.is_evicted_by_pods_ready_timeout(info.obj) is None:
            return True
        return self.clock.now() >= rs.requeue_at

    def pop(self) -> Optional[wlpkg.Info]:
        with self._lock:
            self.pop_cycle += 1
            info = self.heap.pop()
            self.inflight = info
            return info

    def delete(self, wl: api.Workload) -> None:
        with self._lock:
            key = wlpkg.key(wl)
            self.inadmissible.pop(key, None)
            self.heap.delete(key)
            self._forget_inflight(key)

    def _forget_inflight(self, key: str) -> None:
        if self.inflight is not None and self.inflight.key == key:
            self.inflight = None

    # --- requeue (reference: cluster_queue.go:228-255, 405-410) ---

    def requeue_if_not_present(self, info: wlpkg.Info, reason: RequeueReason) -> bool:
        if self.queueing_strategy == api.STRICT_FIFO:
            immediate = reason != RequeueReason.NAMESPACE_MISMATCH
        else:
            immediate = reason in (RequeueReason.FAILED_AFTER_NOMINATION,
                                   RequeueReason.PENDING_PREEMPTION)
        return self._requeue_if_not_present(info, immediate)

    def _requeue_if_not_present(self, info: wlpkg.Info, immediate: bool) -> bool:
        with self._lock:
            key = info.key
            self._forget_inflight(key)
            pending_flavors = (info.last_assignment is not None
                               and info.last_assignment.pending_flavors())
            if self.backoff_expired(info) and (
                    immediate or self.queue_inadmissible_cycle >= self.pop_cycle
                    or pending_flavors):
                parked = self.inadmissible.pop(key, None)
                if parked is not None:
                    info = parked
                return self.heap.push_if_not_present(info)
            if key in self.inadmissible or self.heap.get_by_key(key) is not None:
                return False
            self.inadmissible[key] = info
            return True

    def queue_inadmissible_workloads(self, namespace_labels: Callable) -> bool:
        """Flush parked workloads whose namespace still matches and whose
        backoff expired (reference: cluster_queue.go:265-287).

        namespace_labels(namespace) -> labels dict or None if missing.
        """
        with self._lock:
            self.queue_inadmissible_cycle = self.pop_cycle
            if not self.inadmissible:
                return False
            remaining: dict = {}
            moved = False
            for key, info in self.inadmissible.items():
                labels = namespace_labels(info.obj.metadata.namespace)
                if (labels is None
                        or self.namespace_selector is None
                        or not self.namespace_selector.matches(labels)
                        or not self.backoff_expired(info)):
                    remaining[key] = info
                else:
                    moved = self.heap.push_if_not_present(info) or moved
            self.inadmissible = remaining
            return moved

    # --- introspection ---

    def pending_active(self) -> int:
        with self._lock:
            return len(self.heap) + (1 if self.inflight is not None else 0)

    def pending_inadmissible(self) -> int:
        with self._lock:
            return len(self.inadmissible)

    def pending(self) -> int:
        return self.pending_active() + self.pending_inadmissible()

    def total_elements(self) -> list:
        with self._lock:
            out = self.heap.items() + list(self.inadmissible.values())
            if self.inflight is not None:
                out.append(self.inflight)
            return out

    def snapshot_sorted(self) -> list:
        """All pending workloads in queue order (for visibility API)."""
        import functools
        elements = self.total_elements()
        return sorted(elements, key=functools.cmp_to_key(
            lambda a, b: -1 if self._less(a, b) else 1))

    def dump(self) -> list:
        with self._lock:
            return self.heap.keys()
