"""Queue Manager: LocalQueues -> ClusterQueue heaps -> Heads().

Equivalent of the reference's pkg/queue/manager.go:73-606:
- one ClusterQueueHeap per CQ, LocalQueue item tracking
- Heads() blocks on a condition variable until any CQ head exists, then
  pops at most one head per CQ per cycle
- cohort-wide inadmissible flush when usage changes
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import REAL_CLOCK, Clock
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.queue.cluster_queue import ClusterQueueHeap, RequeueReason


class LocalQueueItems:
    def __init__(self, lq: api.LocalQueue):
        self.key = f"{lq.metadata.namespace}/{lq.metadata.name}"
        self.cluster_queue = lq.spec.cluster_queue
        self.items: dict = {}  # wl key -> Info


class Manager:
    def __init__(self, ordering: Optional[wlpkg.Ordering] = None,
                 clock: Clock = REAL_CLOCK,
                 namespace_labels: Optional[Callable] = None,
                 excluded_resource_prefixes: Optional[list] = None):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.ordering = ordering or wlpkg.Ordering()
        self.clock = clock
        self.cluster_queues: dict = {}  # name -> ClusterQueueHeap
        self.local_queues: dict = {}    # "ns/name" -> LocalQueueItems
        # namespace_labels(ns) -> labels dict or None; default allows all.
        self.namespace_labels = namespace_labels or (lambda ns: {})
        self.excluded_resource_prefixes = excluded_resource_prefixes or []
        self._stopped = False
        self.snapshots: dict = {}  # cq name -> list of pending workloads (visibility)
        # Workload delta feed (solver encode arena): every pending-set
        # mutation that can change a workload's encoded rows notifies
        # the registered listeners, so derived per-workload state is
        # maintained by deltas instead of rescanned per cycle.
        self._workload_listeners: list = []
        # Info-carrying variant of the same feed (journey ledger,
        # obs/journey.py): cb(kind, key, info) — the arrival hook needs
        # the Info (creation timestamp, CQ, class labels), which the
        # key-only arena feed deliberately omits.
        self._journey_listeners: list = []

    def add_workload_listener(self, cb: Callable[[str, str], None]) -> None:
        """Register cb(kind, key): 'upsert' = the workload was added or
        its object replaced (any derived encoding is stale); 'del' = it
        left the pending set. Called under the manager lock — listeners
        must only enqueue, never block or call back into the manager.
        Requeues of an unchanged Info deliberately do NOT notify: the
        common per-cycle requeue churn must keep derived rows valid."""
        with self._lock:
            self._workload_listeners.append(cb)

    def add_journey_listener(self, cb: Callable[[str, str, object], None]
                             ) -> None:
        """Like add_workload_listener, but cb(kind, key, info) carries
        the Info (None when the mutator no longer holds it). Same
        contract: fired under the manager lock, listeners must only
        record, never call back."""
        with self._lock:
            self._journey_listeners.append(cb)

    def _notify(self, kind: str, key: str, info=None) -> None:
        for cb in self._workload_listeners:
            cb(kind, key)
        for cb in self._journey_listeners:
            cb(kind, key, info)

    def _new_info(self, wl: api.Workload) -> wlpkg.Info:
        return wlpkg.Info(wl, excluded_resource_prefixes=self.excluded_resource_prefixes)

    def any_strict_fifo(self) -> bool:
        """True when any CQ uses StrictFIFO: its requeued head must block
        the queue, so the scheduler may not pop the next cycle's heads
        before the previous cycle's requeues (pipelined dispatch gate)."""
        with self._lock:
            return any(cqh.queueing_strategy == api.STRICT_FIFO
                       for cqh in self.cluster_queues.values())

    # --- ClusterQueues ---

    def add_cluster_queue(self, cq: api.ClusterQueue) -> None:
        with self._lock:
            name = cq.metadata.name
            if name in self.cluster_queues:
                return
            cqh = ClusterQueueHeap(cq, self.ordering, self.clock)
            self.cluster_queues[name] = cqh
            # Adopt pending workloads from matching LocalQueues.
            added = False
            for lq in self.local_queues.values():
                if lq.cluster_queue == name:
                    for info in lq.items.values():
                        added = cqh.heap.push_if_not_present(info) or added
            if added:
                self._cond.notify_all()

    def update_cluster_queue(self, cq: api.ClusterQueue, spec_updated: bool = True) -> None:
        with self._lock:
            cqh = self.cluster_queues.get(cq.metadata.name)
            if cqh is None:
                return
            old_strategy = cqh.queueing_strategy
            cqh.update(cq)
            if spec_updated or old_strategy != cqh.queueing_strategy:
                if cqh.queue_inadmissible_workloads(self.namespace_labels):
                    self._cond.notify_all()

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            self.cluster_queues.pop(name, None)
            self.snapshots.pop(name, None)

    # --- LocalQueues ---

    def add_local_queue(self, lq: api.LocalQueue, workloads: Optional[list] = None) -> None:
        """workloads: pre-existing Workloads pointing at this queue
        (reference lists them from the informer cache)."""
        with self._lock:
            items = LocalQueueItems(lq)
            if items.key in self.local_queues:
                return
            self.local_queues[items.key] = items
            for wl in workloads or []:
                if wl.spec.queue_name != lq.metadata.name or wlpkg.has_quota_reservation(wl):
                    continue
                info = self._new_info(wl)
                info.cluster_queue = items.cluster_queue
                items.items[wlpkg.key(wl)] = info
                self._notify("upsert", wlpkg.key(wl), info)
            cqh = self.cluster_queues.get(items.cluster_queue)
            if cqh is not None:
                added = False
                for info in items.items.values():
                    added = cqh.heap.push_if_not_present(info) or added
                if added:
                    self._cond.notify_all()

    def update_local_queue(self, lq: api.LocalQueue) -> None:
        with self._lock:
            key = f"{lq.metadata.namespace}/{lq.metadata.name}"
            items = self.local_queues.get(key)
            if items is None or items.cluster_queue == lq.spec.cluster_queue:
                return
            old_cq = self.cluster_queues.get(items.cluster_queue)
            if old_cq is not None:
                for info in items.items.values():
                    old_cq.delete(info.obj)
            items.cluster_queue = lq.spec.cluster_queue
            # The target ClusterQueue changed: every member's encoded
            # rows are keyed to the old CQ — invalidate the arena rows
            # (feed) AND the per-Info oracle cache, which keys only on
            # (topo token, resourceVersion) and would otherwise serve
            # the old CQ's row.
            for info in items.items.values():
                info._solver_enc = None
                self._notify("upsert", info.key, info)
            new_cq = self.cluster_queues.get(items.cluster_queue)
            if new_cq is not None:
                added = False
                for info in items.items.values():
                    added = new_cq.heap.push_if_not_present(info) or added
                if added:
                    self._cond.notify_all()

    def delete_local_queue(self, lq: api.LocalQueue) -> None:
        with self._lock:
            key = f"{lq.metadata.namespace}/{lq.metadata.name}"
            items = self.local_queues.pop(key, None)
            if items is None:
                return
            cqh = self.cluster_queues.get(items.cluster_queue)
            for info in items.items.values():
                if cqh is not None:
                    cqh.delete(info.obj)
                self._notify("del", info.key, info)

    # --- workload flow ---

    def add_or_update_workload(self, wl: api.Workload) -> bool:
        with self._lock:
            return self._add_or_update_workload_locked(wl)

    def _add_or_update_workload_locked(self, wl: api.Workload) -> bool:
        items = self.local_queues.get(wlpkg.queue_key(wl))
        if items is None:
            return False
        info = self._new_info(wl)
        info.cluster_queue = items.cluster_queue
        items.items[info.key] = info
        self._notify("upsert", info.key, info)
        cqh = self.cluster_queues.get(items.cluster_queue)
        if cqh is None:
            return False
        cqh.push_or_update(info)
        self._cond.notify_all()
        return True

    def update_workload(self, old: api.Workload, new: api.Workload) -> bool:
        with self._lock:
            if old.spec.queue_name != new.spec.queue_name:
                self._delete_workload_locked(old)
            return self._add_or_update_workload_locked(new)

    def delete_workload(self, wl: api.Workload) -> None:
        with self._lock:
            self._delete_workload_locked(wl)

    def _delete_workload_locked(self, wl: api.Workload) -> None:
        items = self.local_queues.get(wlpkg.queue_key(wl))
        if items is not None:
            info = items.items.pop(wlpkg.key(wl), None)
            if info is not None:
                self._notify("del", wlpkg.key(wl), info)
            cqh = self.cluster_queues.get(items.cluster_queue)
            if cqh is not None:
                cqh.delete(wl)

    def requeue_workload(self, info: wlpkg.Info, reason: RequeueReason) -> bool:
        """reference: manager.go:325 — re-fetches the workload upstream;
        here the caller passes the current Info."""
        with self._lock:
            if wlpkg.has_quota_reservation(info.obj) or not wlpkg.is_active(info.obj):
                return False
            items = self.local_queues.get(wlpkg.queue_key(info.obj))
            if items is None:
                return False
            items.items[info.key] = info
            cqh = self.cluster_queues.get(items.cluster_queue)
            if cqh is None:
                return False
            added = cqh.requeue_if_not_present(info, reason)
            if added:
                self._cond.notify_all()
            return added

    def queue_for_workload_exists(self, wl: api.Workload) -> bool:
        with self._lock:
            return wlpkg.queue_key(wl) in self.local_queues

    def cluster_queue_for_workload(self, wl: api.Workload) -> Optional[str]:
        with self._lock:
            items = self.local_queues.get(wlpkg.queue_key(wl))
            if items is None:
                return None
            if items.cluster_queue in self.cluster_queues:
                return items.cluster_queue
            return None

    # --- inadmissible flushing (reference: manager.go:381-450) ---

    def queue_associated_inadmissible_workloads_after(self, wl: api.Workload,
                                                      action: Optional[Callable] = None) -> None:
        """After a workload releases quota, flush the whole cohort's parked
        workloads (reference: manager.go:381)."""
        with self._lock:
            if action:
                action()
            if wl.status.admission is None:
                return
            cqh = self.cluster_queues.get(wl.status.admission.cluster_queue)
            if cqh is None:
                return
            self._queue_all_inadmissible_in_cohort(cqh)

    def queue_inadmissible_workloads(self, cq_names: set) -> None:
        with self._lock:
            queued = False
            for name in cq_names:
                cqh = self.cluster_queues.get(name)
                if cqh is None:
                    continue
                queued = self._queue_all_inadmissible_in_cohort(cqh) or queued
            if queued:
                self._cond.notify_all()

    def _queue_all_inadmissible_in_cohort(self, cqh: ClusterQueueHeap) -> bool:
        queued = False
        if cqh.cohort:
            for other in self.cluster_queues.values():
                if other.cohort == cqh.cohort:
                    queued = other.queue_inadmissible_workloads(self.namespace_labels) or queued
        else:
            queued = cqh.queue_inadmissible_workloads(self.namespace_labels)
        if queued:
            self._cond.notify_all()
        return queued

    # --- heads (reference: manager.go:471-509) ---

    def heads(self, timeout: Optional[float] = None,
              cq_filter=None) -> list:
        """Block until any CQ has a head, then pop one head per CQ.
        Returns [] when stopped (or on timeout if given).
        ``cq_filter(cq_name) -> bool`` restricts the pop to owned CQs —
        an admission shard pops only the CQs its layout assigns it, so
        co-resident shards never race for the same head
        (parallel/shards.py)."""
        with self._cond:
            while not self._stopped:
                h = self._heads_locked(cq_filter)
                if h:
                    return h
                if not self._cond.wait(timeout=timeout):
                    return []
            return []

    def heads_nonblocking(self, cq_filter=None) -> list:
        with self._lock:
            return self._heads_locked(cq_filter)

    def _heads_locked(self, cq_filter=None) -> list:
        out = []
        for cqh in self.cluster_queues.values():
            if not cqh.active:
                continue
            if cq_filter is not None and not cq_filter(cqh.name):
                continue
            info = cqh.pop()
            if info is not None:
                info.cluster_queue = cqh.name
                out.append(info)
        return out

    def set_cluster_queue_active(self, name: str, active: bool) -> None:
        with self._lock:
            cqh = self.cluster_queues.get(name)
            if cqh is not None:
                cqh.active = active
                if active:
                    self._cond.notify_all()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._cond.notify_all()

    def broadcast(self) -> None:
        with self._lock:
            self._cond.notify_all()

    # --- introspection / visibility ---

    def pending(self, cq_name: str) -> int:
        with self._lock:
            cqh = self.cluster_queues.get(cq_name)
            return cqh.pending() if cqh else 0

    def pending_total(self) -> int:
        """Total pending (active + inadmissible) across all CQs."""
        with self._lock:
            return sum(cqh.pending() for cqh in self.cluster_queues.values())

    def pending_workloads_info(self, cq_name: str) -> list:
        return self.pending_order(cq_name)

    def pending_order(self, cq_name: str) -> list:
        """One CQ's pending workloads in queue order WITHOUT taking the
        manager-wide lock: the heap copy runs under the CQ's own lock
        and the sort outside any lock. This is the query plane's
        once-per-cycle-per-CQ table source (obs/queryplane.py) — a
        read-side refresh must never serialize against every other
        CQ's mutations the way the manager-wide lock would (the old
        pending_workloads_info held it across the whole sort).

        Sort-consistency note: the unlocked sort is sound because a
        workload UPDATE replaces its Info object (every mutator builds
        a fresh Info via _new_info and push_or_update swaps it in) —
        the comparator's inputs (priority, queue-order timestamp) are
        immutable per Info instance, so a copied element can never
        change under the comparator mid-sort. In-place Info writes are
        limited to non-ordering fields (cluster_queue, _solver_enc)."""
        cqh = self.cluster_queues.get(cq_name)
        return cqh.snapshot_sorted() if cqh else []

    def pending_workloads_in_local_queue(self, lq_key: str) -> int:
        with self._lock:
            items = self.local_queues.get(lq_key)
            return len(items.items) if items else 0

    def update_snapshot(self, cq_name: str, max_count: int) -> bool:
        """QueueVisibility top-N snapshot (reference: manager.go:566)."""
        with self._lock:
            pending = self.pending_workloads_info(cq_name)[:max_count]
            new = [(info.key, wlpkg.queue_key(info.obj)) for info in pending]
            if self.snapshots.get(cq_name) == new:
                return False
            self.snapshots[cq_name] = new
            return True

    def get_snapshot(self, cq_name: str) -> list:
        with self._lock:
            return list(self.snapshots.get(cq_name, []))
