"""Version stamping (reference: pkg/version)."""

VERSION = "0.1.0"
GIT_COMMIT = "unknown"
