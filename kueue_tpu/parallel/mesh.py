"""Cohort-parallel sharded admission solve.

The scaling axis of the reference is head-of-queue width x flavor count x
cohort depth (SURVEY.md §5). Conflict domains — root cohorts, plus a
synthetic domain per cohortless CQ — are *independent capacity domains*:
workloads in different domains never contend for the same quota
(reference: all fit/borrow math walks within one cohort tree,
pkg/cache/resource_node.go). That makes the domain the natural SPMD axis.

v3 (both phases partitioned): ONE dispatch per cycle.

- Phase A (the FLOP bulk: [W,F,R] flavor assignment) is sharded over the
  WORKLOAD axis — each device assigns flavors for its W/n slice of the
  batch against the replicated pre-cycle usage (per-workload assignment
  is embarrassingly parallel: it reads only snapshot state), then one
  all_gather rebuilds the full batch before the order-grid build.
- Phase B is sharded over the conflict-domain axis — root cohorts (plus
  a synthetic domain per cohortless CQ) are independent capacity
  domains: workloads in different domains never contend for the same
  quota (reference: all fit/borrow math walks within one cohort tree,
  pkg/cache/resource_node.go), so each device scans only its own grid
  columns and the disjoint usage deltas combine with a single psum.

ICI/DCN traffic per cycle: one replicated broadcast of the batch in, one
all_gather of Phase A outputs between phases, one psum of usage deltas +
admitted masks out. Decisions are bit-identical to the single-chip path
(differentially checked by __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kueue_tpu.solver.kernel import (
    _cohort_avail,
    _drf_share,
    _phase_a,
    max_rank_bound,
    solve_phase_b_domains_impl,
)


def make_mesh(devices=None, axis_name: str = "cohorts") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def solve_cycle_sharded(mesh: Mesh, topo: dict, state, batch, num_podsets: int,
                        fair_sharing: bool = False, start_rank=None):
    """Run the fused admission cycle SPMD over the mesh, partitioning the
    conflict-domain axis across devices."""
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    C = topo["cohort_subtree"].shape[0]
    Q = topo["cq_cohort"].shape[0]
    D = C + Q
    d_local = -(-D // n_dev)  # ceil
    d_pad = d_local * n_dev
    max_rank = max_rank_bound(batch.wl_cq, topo["cq_cohort"],
                              topo["cohort_root"])

    def body(topo_, usage, cohort_usage, requests, podset_active, wl_cq,
             priority, timestamp, eligible, solvable, start_rank_):
        W = requests.shape[0]
        dev = jax.lax.axis_index(axis)

        # --- Phase A sharded over W: this device assigns flavors for its
        # own workload slice against the (replicated) pre-cycle usage ---
        w_local = -(-W // n_dev)
        w_pad = w_local * n_dev

        def wslice(a):
            if w_pad != W:
                pad = [(0, w_pad - W)] + [(0, 0)] * (a.ndim - 1)
                a = jnp.pad(a, pad)
            return jax.lax.dynamic_slice_in_dim(a, dev * w_local, w_local, 0)

        cohort_avail = _cohort_avail(topo_, cohort_usage)
        fit_l, borrows_l, chosen_l, chosen_borrow_l, asg_usage_l = _phase_a(
            topo_, usage, cohort_avail, wslice(requests),
            wslice(podset_active), wslice(wl_cq), wslice(eligible),
            wslice(solvable), num_podsets,
            wslice(start_rank_) if start_rank_ is not None else None)

        def gather(a):
            out = jax.lax.all_gather(a, axis, axis=0, tiled=True)
            return out[:W] if w_pad != W else out

        # one all_gather rebuilds the full batch for the grid build
        fit = gather(fit_l)
        borrows = gather(borrows_l)
        chosen = gather(chosen_l)
        chosen_borrow = gather(chosen_borrow_l)
        asg_usage = gather(asg_usage_l)
        share = (_drf_share(topo_, usage, asg_usage, wl_cq) if fair_sharing
                 else jnp.zeros(W, jnp.int64))
        order = jnp.lexsort((timestamp, -priority, share,
                             borrows.astype(jnp.int32),
                             (~fit).astype(jnp.int32)))
        cohort_of = topo_["cq_cohort"][wl_cq]
        root_of = topo_["cohort_root"][jnp.maximum(cohort_of, 0)]
        domain = jnp.where(cohort_of >= 0, root_of.astype(jnp.int32),
                           C + wl_cq.astype(jnp.int32))
        dom_of_order = domain[order]
        perm = jnp.argsort(dom_of_order, stable=True)
        sorted_dom = dom_of_order[perm]
        pos = jnp.arange(W)
        first = jnp.concatenate([jnp.ones(1, bool),
                                 sorted_dom[1:] != sorted_dom[:-1]])
        seg_start = jax.lax.cummax(jnp.where(first, pos, 0))
        rank_sorted = pos - seg_start
        grid = jnp.full((max_rank, d_pad), -1, jnp.int32)
        grid = grid.at[rank_sorted, sorted_dom].set(
            order[perm].astype(jnp.int32), mode="drop")

        # --- partitioned: this device scans columns d ≡ dev (mod n) ---
        grid_local = grid.reshape(max_rank, d_local, n_dev)[:, :, dev]
        admitted, usage_out, cohort_out = solve_phase_b_domains_impl(
            topo_, usage, cohort_usage, asg_usage, fit, wl_cq, grid_local)

        # disjoint domains => disjoint deltas; combine with psum
        admitted = jax.lax.psum(admitted.astype(jnp.int32), axis) > 0
        usage_out = usage + jax.lax.psum(usage_out - usage, axis)
        cohort_out = cohort_usage + jax.lax.psum(cohort_out - cohort_usage,
                                                 axis)
        return {"admitted": admitted, "chosen": chosen,
                "borrows": borrows, "chosen_borrow": chosen_borrow,
                "fit": fit, "usage": usage_out, "cohort_usage": cohort_out}

    if start_rank is None:
        start_rank = np.zeros(batch.requests.shape, np.int32)
    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(),) * 11,
        out_specs=P(),
        check_vma=False)
    return jax.jit(sharded)(
        topo, state.usage, state.cohort_usage, batch.requests,
        batch.podset_active, batch.wl_cq, batch.priority, batch.timestamp,
        batch.eligible, batch.solvable, start_rank)


def per_device_scan_width(num_cqs: int, num_cohorts: int, n_dev: int) -> tuple:
    """(replicated width, per-device width) of one Phase B scan row —
    the measured work reduction the partitioning buys."""
    D = num_cqs + num_cohorts
    return D, -(-D // n_dev)
