"""Cohort-parallel sharded admission solve.

The scaling axis of the reference is head-of-queue width x flavor count x
cohort depth (SURVEY.md §5). Conflict domains — root cohorts, plus a
synthetic domain per cohortless CQ — are *independent capacity domains*:
workloads in different domains never contend for the same quota
(reference: all fit/borrow math walks within one cohort tree,
pkg/cache/resource_node.go). That makes the domain the natural SPMD axis.

v3 (both phases partitioned): ONE dispatch per cycle.

- Phase A (the FLOP bulk: [W,F,R] flavor assignment) is sharded over the
  WORKLOAD axis — each device assigns flavors for its W/n slice of the
  batch against the replicated pre-cycle usage (per-workload assignment
  is embarrassingly parallel: it reads only snapshot state), then one
  all_gather rebuilds the full batch before the order-grid build.
- Phase B is sharded over the conflict-domain axis — root cohorts (plus
  a synthetic domain per cohortless CQ) are independent capacity
  domains: workloads in different domains never contend for the same
  quota (reference: all fit/borrow math walks within one cohort tree,
  pkg/cache/resource_node.go), so each device scans only its own grid
  columns and the disjoint usage deltas combine with a single psum.

When the cycle carries a preemption batch, the batched minimalPreemptions
program is FUSED into the same execute, sharded over the PROBLEM axis
(each problem's simulation is independent of every other's): one
dispatch, one sync, for mixed admission+preemption cycles — matching the
single-chip solve_cycle_with_preempt (VERDICT r3 weak #6).

ICI/DCN traffic per cycle: one replicated broadcast of the batch in, one
all_gather of Phase A outputs between phases, one psum of usage deltas +
admitted masks out (+ one all_gather of preemption targets when fused).
Decisions are bit-identical to the single-chip path (differentially
checked by __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kueue_tpu.solver.kernel import (
    _cohort_avail,
    _drf_share,
    _phase_a,
    max_rank_bound,
    solve_phase_b_domains_impl,
)


def make_mesh(devices=None, axis_name: str = "cohorts") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


# Compiled sharded cycles, keyed on everything that changes the traced
# program (argument shapes re-key through jit's own tracing cache).
# LRU-bounded: max_rank is part of the key and varies per cycle, so a
# workload mix with many hot variants must evict one-at-a-time instead
# of thrashing the whole cache.
from collections import OrderedDict

_SHARDED_CACHE: OrderedDict = OrderedDict()


def solve_cycle_sharded(mesh: Mesh, topo: dict, state, batch, num_podsets: int,
                        fair_sharing: bool = False, start_rank=None,
                        preempt_args=None):
    """Run the fused admission cycle SPMD over the mesh, partitioning the
    conflict-domain axis across devices."""
    max_rank = max_rank_bound(batch.wl_cq, topo["cq_cohort"],
                              topo["cohort_root"])
    key = (id(mesh), num_podsets, bool(fair_sharing), max_rank,
           preempt_args is not None)
    fn = _SHARDED_CACHE.get(key)
    if fn is None:
        if len(_SHARDED_CACHE) >= 16:
            # Bound executable + Mesh retention (test suites build many
            # meshes; max_rank varies per cycle): drop the least recently
            # used entry only.
            _SHARDED_CACHE.popitem(last=False)
        fn = _build_sharded(mesh, num_podsets, fair_sharing, max_rank,
                            preempt_args is not None)
        _SHARDED_CACHE[key] = fn
    else:
        _SHARDED_CACHE.move_to_end(key)
    if start_rank is None:
        start_rank = np.zeros(batch.requests.shape, np.int32)
    args = (topo, state.usage, state.cohort_usage, batch.requests,
            batch.podset_active, batch.wl_cq, batch.priority,
            batch.timestamp, batch.eligible, batch.solvable, start_rank)
    if preempt_args is not None:
        return fn(*args, preempt_args)
    return fn(*args)


def _build_sharded(mesh: Mesh, num_podsets: int, fair_sharing: bool,
                   max_rank: int, with_preempt: bool):
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size

    def body(topo_, usage, cohort_usage, requests, podset_active, wl_cq,
             priority, timestamp, eligible, solvable, start_rank_,
             pargs=None):
        C = topo_["cohort_subtree"].shape[0]
        Q = topo_["cq_cohort"].shape[0]
        D = C + Q
        d_local = -(-D // n_dev)  # ceil
        d_pad = d_local * n_dev
        W = requests.shape[0]
        dev = jax.lax.axis_index(axis)

        # --- Phase A sharded over W: this device assigns flavors for its
        # own workload slice against the (replicated) pre-cycle usage ---
        w_local = -(-W // n_dev)
        w_pad = w_local * n_dev

        def wslice(a):
            if w_pad != W:
                pad = [(0, w_pad - W)] + [(0, 0)] * (a.ndim - 1)
                a = jnp.pad(a, pad)
            return jax.lax.dynamic_slice_in_dim(a, dev * w_local, w_local, 0)

        cohort_avail = _cohort_avail(topo_, cohort_usage)
        fit_l, borrows_l, chosen_l, chosen_borrow_l, asg_usage_l = _phase_a(
            topo_, usage, cohort_avail, wslice(requests),
            wslice(podset_active), wslice(wl_cq), wslice(eligible),
            wslice(solvable), num_podsets,
            wslice(start_rank_) if start_rank_ is not None else None)

        def gather(a):
            out = jax.lax.all_gather(a, axis, axis=0, tiled=True)
            return out[:W] if w_pad != W else out

        # one all_gather rebuilds the full batch for the grid build
        fit = gather(fit_l)
        borrows = gather(borrows_l)
        chosen = gather(chosen_l)
        chosen_borrow = gather(chosen_borrow_l)
        asg_usage = gather(asg_usage_l)
        share = (_drf_share(topo_, usage, asg_usage, wl_cq) if fair_sharing
                 else jnp.zeros(W, jnp.int64))
        order = jnp.lexsort((timestamp, -priority, share,
                             borrows.astype(jnp.int32),
                             (~fit).astype(jnp.int32)))
        cohort_of = topo_["cq_cohort"][wl_cq]
        root_of = topo_["cohort_root"][jnp.maximum(cohort_of, 0)]
        domain = jnp.where(cohort_of >= 0, root_of.astype(jnp.int32),
                           C + wl_cq.astype(jnp.int32))
        dom_of_order = domain[order]
        perm = jnp.argsort(dom_of_order, stable=True)
        sorted_dom = dom_of_order[perm]
        pos = jnp.arange(W)
        first = jnp.concatenate([jnp.ones(1, bool),
                                 sorted_dom[1:] != sorted_dom[:-1]])
        seg_start = jax.lax.cummax(jnp.where(first, pos, 0))
        rank_sorted = pos - seg_start
        grid = jnp.full((max_rank, d_pad), -1, jnp.int32)
        grid = grid.at[rank_sorted, sorted_dom].set(
            order[perm].astype(jnp.int32), mode="drop")

        # --- partitioned: this device scans columns d ≡ dev (mod n) ---
        grid_local = grid.reshape(max_rank, d_local, n_dev)[:, :, dev]
        admitted, usage_out, cohort_out = solve_phase_b_domains_impl(
            topo_, usage, cohort_usage, asg_usage, fit, wl_cq, grid_local)

        # disjoint domains => disjoint deltas; combine with psum
        admitted = jax.lax.psum(admitted.astype(jnp.int32), axis) > 0
        usage_out = usage + jax.lax.psum(usage_out - usage, axis)
        cohort_out = cohort_usage + jax.lax.psum(cohort_out - cohort_usage,
                                                 axis)
        out = {"admitted": admitted, "chosen": chosen,
               "borrows": borrows, "chosen_borrow": chosen_borrow,
               "fit": fit, "usage": usage_out, "cohort_usage": cohort_out}

        if pargs is not None:
            # Fused preemption, sharded over the PROBLEM axis: each
            # problem's simulate/fill-back is independent, so this device
            # solves its B/n slice against the replicated pre-cycle state
            # and one all_gather rebuilds the batch (single dispatch).
            from kueue_tpu.solver.preempt import solve_preempt_impl
            B = pargs[0].shape[0]
            b_local = -(-B // n_dev)
            b_pad = b_local * n_dev

            def bslice(a):
                if b_pad != B:
                    pad = [(0, b_pad - B)] + [(0, 0)] * (a.ndim - 1)
                    a = jnp.pad(a, pad)
                return jax.lax.dynamic_slice_in_dim(a, dev * b_local,
                                                    b_local, 0)

            # cand_usage/cand_prio tables are shared rows — replicated;
            # every other tensor has a leading problem axis.
            from kueue_tpu.solver.preempt import PREEMPT_ARGS_REPLICATED_SLOTS
            sliced = tuple(a if i in PREEMPT_ARGS_REPLICATED_SLOTS
                           else bslice(a) for i, a in enumerate(pargs))
            t_l, f_l, _s_l = solve_preempt_impl(topo_, usage, cohort_usage,
                                                *sliced)

            def bgather(a):
                g = jax.lax.all_gather(a, axis, axis=0, tiled=True)
                return g[:B] if b_pad != B else g

            out["preempt_targets"] = bgather(t_l)
            out["preempt_feasible"] = bgather(f_l)
        return out

    if with_preempt:
        sharded = jax.shard_map(body, mesh=mesh, in_specs=(P(),) * 12,
                                out_specs=P(), check_vma=False)
    else:
        def body_no_pre(*args):
            return body(*args, None)
        sharded = jax.shard_map(body_no_pre, mesh=mesh, in_specs=(P(),) * 11,
                                out_specs=P(), check_vma=False)
    return jax.jit(sharded)


def per_device_scan_width(num_cqs: int, num_cohorts: int, n_dev: int) -> tuple:
    """(replicated width, per-device width) of one Phase B scan row —
    the measured work reduction the partitioning buys."""
    D = num_cqs + num_cohorts
    return D, -(-D // n_dev)
