"""Cohort-parallel sharded admission solve.

The scaling axis of the reference is head-of-queue width x flavor count x
cohort depth (SURVEY.md §5). Cohorts are *independent capacity domains*:
workloads in different cohorts never contend for the same quota
(reference: all fit/borrow math walks within one cohort tree,
pkg/cache/resource_node.go). That makes the cohort the natural SPMD axis:
each device solves the full cycle for the cohorts it owns, and decisions
are combined with a single psum — no sequential cross-device dependency.

ICI/DCN traffic per cycle: one replicated broadcast of the batch in, one
psum of usage deltas + admitted masks out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kueue_tpu.solver.kernel import solve_cycle_impl


def make_mesh(devices=None, axis_name: str = "cohorts") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def solve_cycle_sharded(mesh: Mesh, topo: dict, state, batch, num_podsets: int,
                        fair_sharing: bool = False, start_rank=None):
    """Run the batched solve SPMD over the mesh, partitioning capacity
    domains (cohorts, and cohortless CQs) across devices."""
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    C = topo["cohort_subtree"].shape[0]

    def body(topo_, usage, cohort_usage, requests, podset_active, wl_cq,
             priority, timestamp, eligible, solvable, start_rank_):
        dev = jax.lax.axis_index(axis)
        cohort_of_wl = topo_["cq_cohort"][wl_cq]
        root_of_wl = topo_["cohort_root"][jnp.maximum(cohort_of_wl, 0)]
        # capacity domain id: root cohort index (whole tree = one
        # domain), or C + cq index for lone CQs
        domain = jnp.where(cohort_of_wl >= 0, root_of_wl,
                           C + wl_cq.astype(jnp.int32))
        mine = (domain % n_dev) == dev
        res = solve_cycle_impl(topo_, usage, cohort_usage, requests,
                               podset_active, wl_cq, priority, timestamp,
                               eligible, solvable & mine, num_podsets,
                               fair_sharing=fair_sharing,
                               start_rank=start_rank_)
        usage_delta = res["usage"] - usage
        cohort_delta = res["cohort_usage"] - cohort_usage
        admitted = jax.lax.psum(res["admitted"].astype(jnp.int32), axis) > 0
        usage_out = usage + jax.lax.psum(usage_delta, axis)
        cohort_out = cohort_usage + jax.lax.psum(cohort_delta, axis)
        # chosen flavors are computed identically on every device (phase A
        # is deterministic given the snapshot); take them as-is.
        return {"admitted": admitted, "chosen": res["chosen"],
                "borrows": res["borrows"],
                "chosen_borrow": res["chosen_borrow"], "fit": res["fit"],
                "usage": usage_out, "cohort_usage": cohort_out}

    if start_rank is None:
        start_rank = np.zeros(batch.requests.shape, np.int32)
    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(),) * 11,
        out_specs=P(),
        check_vma=False)
    return jax.jit(sharded)(
        topo, state.usage, state.cohort_usage, batch.requests,
        batch.podset_active, batch.wl_cq, batch.priority, batch.timestamp,
        batch.eligible, batch.solvable, start_rank)
