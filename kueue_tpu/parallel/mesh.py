"""Cohort-parallel sharded admission solve.

The scaling axis of the reference is head-of-queue width x flavor count x
cohort depth (SURVEY.md §5). Conflict domains — root cohorts, plus a
synthetic domain per cohortless CQ — are *independent capacity domains*:
workloads in different domains never contend for the same quota
(reference: all fit/borrow math walks within one cohort tree,
pkg/cache/resource_node.go). That makes the domain the natural SPMD axis.

v2 (real partitioning): ONE dispatch per cycle. Every device runs the
cheap replicated parts (Phase A flavor assignment, the device-built
order grid) and then scans only ITS OWN slice of the grid's domain
columns — per-device Phase B work shrinks ~linearly with the mesh size
(row width D/n instead of D). Distinct domains touch disjoint CQ/cohort
state, so the per-device usage deltas combine with a single psum.

ICI/DCN traffic per cycle: one replicated broadcast of the batch in, one
psum of usage deltas + admitted masks out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kueue_tpu.solver.kernel import (
    _cohort_avail,
    _drf_share,
    _phase_a,
    max_rank_bound,
    solve_phase_b_domains_impl,
)


def make_mesh(devices=None, axis_name: str = "cohorts") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def solve_cycle_sharded(mesh: Mesh, topo: dict, state, batch, num_podsets: int,
                        fair_sharing: bool = False, start_rank=None):
    """Run the fused admission cycle SPMD over the mesh, partitioning the
    conflict-domain axis across devices."""
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    C = topo["cohort_subtree"].shape[0]
    Q = topo["cq_cohort"].shape[0]
    D = C + Q
    d_local = -(-D // n_dev)  # ceil
    d_pad = d_local * n_dev
    max_rank = max_rank_bound(batch.wl_cq, topo["cq_cohort"],
                              topo["cohort_root"])

    def body(topo_, usage, cohort_usage, requests, podset_active, wl_cq,
             priority, timestamp, eligible, solvable, start_rank_):
        W = requests.shape[0]
        dev = jax.lax.axis_index(axis)

        # --- replicated: Phase A + admit order + domain-rank grid ---
        cohort_avail = _cohort_avail(topo_, cohort_usage)
        fit, borrows, chosen, chosen_borrow, asg_usage = _phase_a(
            topo_, usage, cohort_avail, requests, podset_active, wl_cq,
            eligible, solvable, num_podsets, start_rank_)
        share = (_drf_share(topo_, usage, asg_usage, wl_cq) if fair_sharing
                 else jnp.zeros(W, jnp.int64))
        order = jnp.lexsort((timestamp, -priority, share,
                             borrows.astype(jnp.int32),
                             (~fit).astype(jnp.int32)))
        cohort_of = topo_["cq_cohort"][wl_cq]
        root_of = topo_["cohort_root"][jnp.maximum(cohort_of, 0)]
        domain = jnp.where(cohort_of >= 0, root_of.astype(jnp.int32),
                           C + wl_cq.astype(jnp.int32))
        dom_of_order = domain[order]
        perm = jnp.argsort(dom_of_order, stable=True)
        sorted_dom = dom_of_order[perm]
        pos = jnp.arange(W)
        first = jnp.concatenate([jnp.ones(1, bool),
                                 sorted_dom[1:] != sorted_dom[:-1]])
        seg_start = jax.lax.cummax(jnp.where(first, pos, 0))
        rank_sorted = pos - seg_start
        grid = jnp.full((max_rank, d_pad), -1, jnp.int32)
        grid = grid.at[rank_sorted, sorted_dom].set(
            order[perm].astype(jnp.int32), mode="drop")

        # --- partitioned: this device scans columns d ≡ dev (mod n) ---
        grid_local = grid.reshape(max_rank, d_local, n_dev)[:, :, dev]
        admitted, usage_out, cohort_out = solve_phase_b_domains_impl(
            topo_, usage, cohort_usage, asg_usage, fit, wl_cq, grid_local)

        # disjoint domains => disjoint deltas; combine with psum
        admitted = jax.lax.psum(admitted.astype(jnp.int32), axis) > 0
        usage_out = usage + jax.lax.psum(usage_out - usage, axis)
        cohort_out = cohort_usage + jax.lax.psum(cohort_out - cohort_usage,
                                                 axis)
        return {"admitted": admitted, "chosen": chosen,
                "borrows": borrows, "chosen_borrow": chosen_borrow,
                "fit": fit, "usage": usage_out, "cohort_usage": cohort_out}

    if start_rank is None:
        start_rank = np.zeros(batch.requests.shape, np.int32)
    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(),) * 11,
        out_specs=P(),
        check_vma=False)
    return jax.jit(sharded)(
        topo, state.usage, state.cohort_usage, batch.requests,
        batch.podset_active, batch.wl_cq, batch.priority, batch.timestamp,
        batch.eligible, batch.solvable, start_rank)


def per_device_scan_width(num_cqs: int, num_cohorts: int, n_dev: int) -> tuple:
    """(replicated width, per-device width) of one Phase B scan row —
    the measured work reduction the partitioning buys."""
    D = num_cqs + num_cohorts
    return D, -(-D // n_dev)
