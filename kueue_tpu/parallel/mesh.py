"""Cohort-parallel sharded admission solve over single- AND multi-host
meshes.

The scaling axis of the reference is head-of-queue width x flavor count
x cohort depth (SURVEY.md §5). Conflict domains — root cohorts, plus a
synthetic domain per cohortless CQ — are *independent capacity domains*:
workloads in different domains never contend for the same quota
(reference: all fit/borrow math walks within one cohort tree,
pkg/cache/resource_node.go). That makes the domain the natural SPMD axis.

v4 (multi-host DCN + first-class domain planner): ONE dispatch per
cycle, over a one-axis ``("cohorts",)`` mesh (single host) or a
two-axis ``("hosts", "cohorts")`` mesh (multi-host DCN; simulate
locally via ``XLA_FLAGS=--xla_force_host_platform_device_count=N``, or
deploy for real through ``init_distributed()``/``jax.distributed``).

- Phase A (the FLOP bulk: [W,F,R] flavor assignment) is sharded over
  the WORKLOAD axis across ALL devices of BOTH axes — per-workload
  assignment reads only replicated snapshot state — then one
  all_gather rebuilds the full batch before the order-grid build.
- Phase B is sharded over PLANNER-ASSIGNED conflict domains
  (parallel/domains.py): the planner cost-balances OCCUPIED domains
  (weight = workload count x flavor width) across devices instead of
  the old round-robin over the mostly-empty C+Q domain space, and each
  device gathers exactly its assigned grid columns. Disjoint usage
  deltas combine with a staged psum: ICI first (the intra-host
  "cohorts" axis), then DCN (the "hosts" axis) — the only tensors that
  cross hosts in Phase B are the small per-domain reduction outputs
  (usage deltas + admitted masks), never the [W,F,R] assignment bulk.
- Preemption batches FUSE into the same execute, sharded over the
  PROBLEM axis through the same planner (problems weighted by
  candidate-pool size, outputs un-permuted after the gather).
- MultiKueue remote-cluster capacity columns
  (kernel.score_cluster_columns_impl) score replicated inside the same
  program — tiny [K,F,R] state, no extra collective.

Decisions are bit-identical to the single-chip fused path and to any
other mesh shape over the same batch (differentially checked by
__graft_entry__.dryrun_multichip, tools/mesh_probe.py and
tests/test_domains.py).

Compiled executables are cached per (mesh fingerprint, program
variant): the fingerprint covers the FULL mesh shape, axis names and
device set — a re-built mesh over a different host count can never be
served a stale sharded executable (the pre-v4 cache keyed on
``id(mesh)``, which a recycled allocation could collide).
"""

from __future__ import annotations

import os
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kueue_tpu.parallel.domains import DomainPlan, plan_domains, plan_problems
from kueue_tpu.solver.kernel import (
    _cohort_avail,
    _drf_share,
    _phase_a,
    max_rank_bound,
    score_cluster_columns_impl,
    solve_phase_b_domains_impl,
)


def _shard_map(f, mesh, in_specs, out_specs):
    """Version shim: jax.shard_map(check_vma=) on current jax,
    jax.experimental.shard_map(check_rep=) on the 0.4.x line the
    accelerator-free containers pin."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def make_mesh(devices=None, axis_name: str = "cohorts") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def make_host_mesh(devices=None, hosts: int = None) -> Mesh:
    """Two-axis ``("hosts", "cohorts")`` mesh: the major axis groups
    devices by host (DCN between groups), the minor axis is the
    intra-host device axis (ICI). With real multi-host jax
    (jax.distributed initialized) devices are grouped by their
    process_index; under a forced host-platform device count the first
    axis SIMULATES hosts by folding the flat device list."""
    devices = list(devices if devices is not None else jax.devices())
    if hosts is None:
        hosts = max(len({d.process_index for d in devices}), 1)
    n = len(devices)
    if n % hosts != 0:
        raise ValueError(f"{n} devices do not fold into {hosts} hosts")
    if hosts > 1 and len({d.process_index for d in devices}) == hosts:
        # real multi-host: keep each host's devices on its own row
        devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    grid = np.asarray(devices).reshape(hosts, n // hosts)
    return Mesh(grid, ("hosts", "cohorts"))


def init_distributed(coordinator: str = None, num_processes: int = None,
                     process_id: int = None) -> bool:
    """Real-deployment path: initialize jax.distributed from arguments
    or the standard env (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID). Returns False (no-op) when nothing is configured —
    the local simulate-by-forced-device-count path needs no init."""
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None:
        return False
    kwargs = {"coordinator_address": coordinator}
    num_processes = num_processes or os.environ.get("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None \
        else os.environ.get("JAX_PROCESS_ID")
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    jax.distributed.initialize(**kwargs)
    return True


def mesh_fingerprint(mesh: Mesh) -> tuple:
    """Stable identity of the mesh LAYOUT: axis names, full shape and
    the ordered device set. Keys the executable cache (below) and the
    warm-ladder topology fingerprint (solver/warmgov.py) — two Mesh
    objects over the same layout share executables; meshes differing
    in host count (or any device) never collide."""
    return (tuple(mesh.axis_names), mesh.devices.shape,
            tuple(int(d.id) for d in mesh.devices.flat))


# Compiled sharded cycles, keyed on the mesh FINGERPRINT plus everything
# else that changes the traced program (argument shapes — the planner's
# bucketed column count included — re-key through jit's own tracing
# cache). LRU-bounded: max_rank is part of the key and varies per cycle,
# so a workload mix with many hot variants must evict one-at-a-time
# instead of thrashing the whole cache.
_SHARDED_CACHE: OrderedDict = OrderedDict()

def plan_cycle(mesh: Mesh, topo, batch, topo_np=None) -> DomainPlan:
    """The cycle's domain->device plan (parallel/domains.py). Uses the
    host Topology when the caller has one (the production service
    always does — zero device reads); tooling/dryrun callers without
    one pay a per-call device->host read of the small planner inputs
    (deliberately uncached: memoizing by topo-dict identity would pin
    retired epochs' device tensors alive)."""
    n_dev = int(mesh.devices.size)
    if topo_np is not None:
        cq_cohort, cohort_root, offered = (topo_np.cq_cohort,
                                           topo_np.cohort_root,
                                           topo_np.offered)
    else:
        cq_cohort = np.asarray(topo["cq_cohort"])
        cohort_root = np.asarray(topo["cohort_root"])
        offered = np.asarray(topo["offered"])
    return plan_domains(np.asarray(batch.wl_cq), cq_cohort, cohort_root,
                        offered, n_dev)


def solve_cycle_sharded(mesh: Mesh, topo: dict, state, batch,
                        num_podsets: int, fair_sharing: bool = False,
                        start_rank=None, preempt_args=None, topo_np=None,
                        cluster_args=None, preempt_weights=None,
                        plan: DomainPlan = None):
    """Run the fused admission cycle SPMD over the mesh (one or two
    axes), partitioning the conflict-domain axis across devices by the
    planner's cost-balanced layout."""
    max_rank = max_rank_bound(batch.wl_cq, topo["cq_cohort"],
                              topo["cohort_root"])
    key = (mesh_fingerprint(mesh), num_podsets, bool(fair_sharing),
           max_rank, preempt_args is not None, cluster_args is not None)
    fn = _SHARDED_CACHE.get(key)
    if fn is None:
        if len(_SHARDED_CACHE) >= 16:
            # Bound executable + Mesh retention (test suites build many
            # meshes; max_rank varies per cycle): drop the least recently
            # used entry only.
            _SHARDED_CACHE.popitem(last=False)
        fn = _build_sharded(mesh, num_podsets, fair_sharing, max_rank,
                            preempt_args is not None,
                            cluster_args is not None)
        _SHARDED_CACHE[key] = fn
    else:
        _SHARDED_CACHE.move_to_end(key)
    if start_rank is None:
        start_rank = np.zeros(batch.requests.shape, np.int32)
    if plan is None:
        plan = plan_cycle(mesh, topo, batch, topo_np=topo_np)
    C = np.asarray(topo["cohort_root"]).shape[0]
    Q = np.asarray(topo["cq_cohort"]).shape[0]
    D = C + Q  # the empty-column sentinel index
    assign = np.where(plan.columns >= 0, plan.columns, D).astype(np.int32)
    args = [topo, state.usage, state.cohort_usage, batch.requests,
            batch.podset_active, batch.wl_cq, batch.priority,
            batch.timestamp, batch.eligible, batch.solvable, start_rank,
            assign]
    if cluster_args is not None:
        args.append(tuple(jnp.asarray(a) for a in cluster_args))
    if preempt_args is not None:
        B = np.asarray(preempt_args[0]).shape[0]
        if preempt_weights is None:
            # candidate-pool size per problem (cand_idx is slot 7,
            # -1-padded) — the simulate/fill-back cost driver
            preempt_weights = np.count_nonzero(
                np.asarray(preempt_args[7]) >= 0, axis=1) + 1
        perm, inv, _b_local = plan_problems(preempt_weights,
                                            int(mesh.devices.size))
        args += [preempt_args, perm.astype(np.int32),
                 inv.astype(np.int32)]
    return fn(*args)


def _axis_layout(mesh: Mesh):
    """(axis name or tuple for collectives, flattened-device-index fn)."""
    axes = tuple(mesh.axis_names)
    if len(axes) == 1:
        return axes[0], lambda: jax.lax.axis_index(axes[0])
    minor = mesh.shape[axes[1]]

    def dev_index():
        return jax.lax.axis_index(axes[0]) * minor + \
            jax.lax.axis_index(axes[1])

    return axes, dev_index


def _build_sharded(mesh: Mesh, num_podsets: int, fair_sharing: bool,
                   max_rank: int, with_preempt: bool, with_clusters: bool):
    axes, dev_index = _axis_layout(mesh)
    axis_names = tuple(mesh.axis_names)
    two_axis = len(axis_names) == 2
    n_dev = int(mesh.devices.size)

    def hier_psum(x):
        """Staged reduction: ICI (intra-host minor axis) first, then the
        DCN-crossing major axis — the only cross-host Phase B traffic
        is this call's (already host-combined) reduction tensors."""
        if two_axis:
            return jax.lax.psum(jax.lax.psum(x, axis_names[1]),
                                axis_names[0])
        return jax.lax.psum(x, axes)

    def body(topo_, usage, cohort_usage, requests, podset_active, wl_cq,
             priority, timestamp, eligible, solvable, start_rank_,
             assign, cargs=None, pargs=None, pperm=None, pinv=None):
        C = topo_["cohort_subtree"].shape[0]
        Q = topo_["cq_cohort"].shape[0]
        D = C + Q
        W = requests.shape[0]
        dev = dev_index()
        d_cols = assign.shape[1]

        # --- Phase A sharded over W across ALL devices: this device
        # assigns flavors for its own workload slice against the
        # (replicated) pre-cycle usage ---
        w_local = -(-W // n_dev)
        w_pad = w_local * n_dev

        def wslice(a):
            if w_pad != W:
                pad = [(0, w_pad - W)] + [(0, 0)] * (a.ndim - 1)
                a = jnp.pad(a, pad)
            return jax.lax.dynamic_slice_in_dim(a, dev * w_local, w_local, 0)

        cohort_avail = _cohort_avail(topo_, cohort_usage)
        fit_l, borrows_l, chosen_l, chosen_borrow_l, asg_usage_l = _phase_a(
            topo_, usage, cohort_avail, wslice(requests),
            wslice(podset_active), wslice(wl_cq), wslice(eligible),
            wslice(solvable), num_podsets,
            wslice(start_rank_) if start_rank_ is not None else None)

        def gather(a):
            out = jax.lax.all_gather(a, axes, axis=0, tiled=True)
            return out[:W] if w_pad != W else out

        # one all_gather rebuilds the full batch for the grid build
        fit = gather(fit_l)
        borrows = gather(borrows_l)
        chosen = gather(chosen_l)
        chosen_borrow = gather(chosen_borrow_l)
        asg_usage = gather(asg_usage_l)
        share = (_drf_share(topo_, usage, asg_usage, wl_cq) if fair_sharing
                 else jnp.zeros(W, jnp.int64))
        order = jnp.lexsort((timestamp, -priority, share,
                             borrows.astype(jnp.int32),
                             (~fit).astype(jnp.int32)))
        cohort_of = topo_["cq_cohort"][wl_cq]
        root_of = topo_["cohort_root"][jnp.maximum(cohort_of, 0)]
        domain = jnp.where(cohort_of >= 0, root_of.astype(jnp.int32),
                           C + wl_cq.astype(jnp.int32))
        dom_of_order = domain[order]
        perm = jnp.argsort(dom_of_order, stable=True)
        sorted_dom = dom_of_order[perm]
        pos = jnp.arange(W)
        first = jnp.concatenate([jnp.ones(1, bool),
                                 sorted_dom[1:] != sorted_dom[:-1]])
        seg_start = jax.lax.cummax(jnp.where(first, pos, 0))
        rank_sorted = pos - seg_start
        # grid over the full domain space + ONE trailing empty column:
        # the planner's padding lanes index it, so duplicated pads scan
        # only invalid (-1) rows — bit-identical no-ops under the psum.
        grid = jnp.full((max_rank, D + 1), -1, jnp.int32)
        grid = grid.at[rank_sorted, sorted_dom].set(
            order[perm].astype(jnp.int32), mode="drop")

        # --- Phase B partitioned by the PLANNER: this device scans
        # exactly its cost-balanced column assignment ---
        my_cols = jax.lax.dynamic_slice_in_dim(
            assign.reshape(-1), dev * d_cols, d_cols, 0)
        grid_local = grid[:, my_cols]
        admitted, usage_out, cohort_out = solve_phase_b_domains_impl(
            topo_, usage, cohort_usage, asg_usage, fit, wl_cq, grid_local)

        # disjoint domains => disjoint deltas; combine ICI-then-DCN
        admitted = hier_psum(admitted.astype(jnp.int32)) > 0
        usage_out = usage + hier_psum(usage_out - usage)
        cohort_out = cohort_usage + hier_psum(cohort_out - cohort_usage)
        out = {"admitted": admitted, "chosen": chosen,
               "borrows": borrows, "chosen_borrow": chosen_borrow,
               "fit": fit, "usage": usage_out, "cohort_usage": cohort_out}

        if cargs is not None:
            # Remote-cluster capacity columns: replicated scoring (the
            # [K,F,R] scan state is tiny; every device computes the
            # identical result — no collective).
            out["mk_cluster"] = score_cluster_columns_impl(
                *cargs, requests, podset_active, wl_cq, order, admitted)

        if pargs is not None:
            # Fused preemption, sharded over the PROBLEM axis through
            # the planner's permutation: each problem's simulate/
            # fill-back is independent, so this device solves its
            # planner-assigned slice against the replicated pre-cycle
            # state; one all_gather + un-permute rebuilds the batch
            # (still a single dispatch).
            from kueue_tpu.solver.preempt import (
                PREEMPT_ARGS_REPLICATED_SLOTS, solve_preempt_impl)
            b_local = pperm.shape[0] // n_dev

            def bslice(a):
                pad = jnp.zeros((1,) + a.shape[1:], a.dtype)
                a_pad = jnp.concatenate([a, pad], axis=0)
                mine = jax.lax.dynamic_slice_in_dim(pperm, dev * b_local,
                                                    b_local, 0)
                return a_pad[mine]

            # cand_usage/cand_prio tables are shared rows — replicated;
            # every other tensor has a leading problem axis.
            sliced = tuple(a if i in PREEMPT_ARGS_REPLICATED_SLOTS
                           else bslice(a) for i, a in enumerate(pargs))
            t_l, f_l, _s_l = solve_preempt_impl(topo_, usage, cohort_usage,
                                                *sliced)

            def bgather(a):
                g = jax.lax.all_gather(a, axes, axis=0, tiled=True)
                return g[pinv]  # un-permute to original problem order

            out["preempt_targets"] = bgather(t_l)
            out["preempt_feasible"] = bgather(f_l)
        return out

    base = 12 + (1 if with_clusters else 0)
    n_args = base + (3 if with_preempt else 0)
    if with_preempt and with_clusters:
        wrapped = body
    elif with_preempt:
        def wrapped(*a):
            return body(*a[:12], None, *a[12:])
    elif with_clusters:
        wrapped = body
    else:
        def wrapped(*a):
            return body(*a)
    sharded = _shard_map(wrapped, mesh, (P(),) * n_args, P())
    return jax.jit(sharded)


def per_device_scan_width(num_cqs: int, num_cohorts: int, n_dev: int,
                          plan: DomainPlan = None) -> tuple:
    """(replicated width, per-device width) of one Phase B scan row —
    the measured work reduction the partitioning buys. With a plan, the
    per-device width is the planner's bucketed column count (occupied
    domains only); without one, the legacy all-domains estimate."""
    D = num_cqs + num_cohorts
    if plan is not None:
        return D, plan.d_cols
    return D, -(-D // n_dev)
