"""Sharded admission control plane (RESILIENCE.md §9, ROADMAP item 1).

The reference Kueue is single-scheduler by design (SURVEY.md L4): one
process owns every queue heap, reconciler and apply loop — both the
throughput wall at the 1M×16k target shape and a single fault domain.
This module composes the safety pieces earlier PRs built (per-lease
fencing epochs, exactly-once store admission records, durable-log
arbitration) into N **admission shards** over one shared watch/store
plane:

- The **plane** is a stock ``KueueManager`` owning the store, the
  durable log, every controller/webhook, the queue heaps and the cache
  — all watch-driven state, maintained exactly once. Its own scheduler
  never admits (leader gate pinned closed); it exists for the shared
  wiring (client, flight recorder, metrics).
- Each **shard** is a leased ``Scheduler`` instance with its OWN
  speculative pipeline, degradation ladder, breaker/watchdog and (when
  a solver is attached) arena + compile governor, popping ONLY the CQs
  the planner assigns it (``Scheduler.cq_filter`` →
  ``queue.Manager.heads``). Shards coordinate exclusively through the
  durable log: each holds a named-lease ``FencingToken``
  (``shard-<i>``), swapped into ``Store.fencing`` for the duration of
  its cycle, so every admission write a shard commits is epoch-checked
  under the log lock — a deposed or zombie shard can never author an
  admission record, and the store's admission records keep cross-shard
  admission exactly-once.
- The **layout** is the planner's (ROADMAP invariant: exactly ONE
  layout decision): ``plan_shards`` rides ``domains.balanced_partition``
  — the same deterministic LPT that places conflict-domain columns on
  devices — over whole cohort subtrees (a preemption victim always
  lives in the preemptor's cohort tree, so whole-cohort assignment
  keeps every victim inside the owning shard's write set). Cohortless
  CQs are their own unit. Cross-shard capacity for future shared
  cohorts scores through the PR-13 cluster-column mechanism the way
  remote clusters already do.

Fault protocol (proven by tools/shard_probe.py, tools/crash_run.py's
shard sweep and the ``shard_rebalance``/``shard_storm`` scenarios):

- **kill**: an ``InjectedCrash`` mid-cycle (the shard's own faultinject
  scope — co-resident shards' scripted schedules are isolated) leaves
  the shard ``killed``: its in-memory pipeline state is discarded like
  a real process death; the shared store/queues/cache are the OTHER
  fault domain and stay live, so surviving shards keep admitting their
  cohorts through the same heaps.
- **promote**: a replacement acquires the shard's named lease under a
  fresh identity — the epoch bump fences the dead shard's zombie
  writes — and a fresh ``Scheduler`` adopts the cohort set with the
  restore() posture (first cycle pinned synchronous). Because the
  plane's watch-driven state never died, promotion is sub-cycle by
  construction: no replay, no rebuild.
- **rebalance**: the planner moves a unit between shards under
  traffic: fence the old owner (epoch bump — its in-flight speculation
  can no longer commit), drain (abandon its pipeline; heads re-heap),
  reassign the layout, and the new owner admits on its next cycle.
"""

from __future__ import annotations

import hashlib
import uuid
from dataclasses import dataclass
from typing import Optional

from kueue_tpu.api.meta import REAL_CLOCK, Clock
from kueue_tpu.parallel.domains import balanced_partition, imbalance_ratio
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.replica import FencingToken
from kueue_tpu.utils import vlog

# Shard lifecycle states (the shard_state{shard} gauge encoding).
SHARD_ACTIVE = "active"
SHARD_KILLED = "killed"   # crashed mid-cycle; awaiting promotion
SHARD_FENCED = "fenced"   # deposed by a newer epoch (zombie)
SHARD_STATE_CODES = {SHARD_ACTIVE: 0, SHARD_KILLED: 1, SHARD_FENCED: 2}

DEFAULT_SHARD_LEASE_S = 15.0


# --- the ONE control-plane layout decision ---------------------------------


def shard_units(cache) -> dict:
    """cq name -> assignment-unit name. The unit is the ROOT cohort
    (whole subtrees move together — preemption victims always live in
    the preemptor's cohort tree, so whole-unit ownership keeps every
    victim inside the owning shard's write set); a cohortless CQ is its
    own unit."""
    units = {}
    for name, cqc in cache.hm.cluster_queues.items():
        c = getattr(cqc, "cohort", None)
        units[name] = f"cohort:{c.root().name}" if c is not None \
            else f"cq:{name}"
    return units


@dataclass(frozen=True)
class ShardPlan:
    """Unit -> shard layout. Deterministic (LPT with stable
    tie-breaks) and fingerprinted the way ``DomainPlan`` is (blake2b
    over the assignment bytes, never ``hash()``), so two processes
    planning from the same topology agree bit-for-bit — the property
    that lets the plan BE the ownership contract."""

    n_shards: int
    units: tuple          # unit names, sorted
    shard_of_unit: dict   # unit name -> shard index
    cq_shard: dict        # cq name -> shard index
    loads: tuple          # per-shard weighted load
    imbalance: float
    fingerprint: str

    def cqs_of(self, shard: int) -> tuple:
        return tuple(sorted(c for c, s in self.cq_shard.items()
                            if s == shard))

    def units_of(self, shard: int) -> tuple:
        return tuple(u for u in self.units
                     if self.shard_of_unit[u] == shard)


def _plan_fingerprint(n_shards: int, units: tuple, bins) -> str:
    h = hashlib.blake2b(digest_size=8)
    h.update(str(n_shards).encode())
    for u, b in zip(units, bins):
        h.update(u.encode())
        h.update(int(b).to_bytes(4, "little"))
    return h.hexdigest()


def plan_shards(cq_units: dict, weights: Optional[dict],
                n_shards: int) -> ShardPlan:
    """Cost-balanced unit -> shard layout over ``cq_units`` (from
    ``shard_units``). ``weights`` maps cq name -> load (pending count,
    flavor width — any monotone cost proxy; default 1 per CQ); a
    unit's weight is the sum over its member CQs, floored at 1 so an
    idle cohort still gets an owner. Rides
    ``domains.balanced_partition`` — the ROADMAP invariant that device
    layout and control-plane layout are the same planner decision."""
    n_shards = max(int(n_shards), 1)
    unit_w: dict = {}
    for cq, unit in cq_units.items():
        w = (weights or {}).get(cq, 1)
        unit_w[unit] = unit_w.get(unit, 0) + max(int(w), 0)
    units = tuple(sorted(unit_w))
    wvec = [max(unit_w[u], 1) for u in units]
    bin_of, loads = balanced_partition(wvec, n_shards)
    shard_of_unit = {u: int(b) for u, b in zip(units, bin_of)}
    cq_shard = {cq: shard_of_unit[unit] for cq, unit in cq_units.items()}
    return ShardPlan(
        n_shards=n_shards, units=units, shard_of_unit=shard_of_unit,
        cq_shard=cq_shard, loads=tuple(int(x) for x in loads),
        imbalance=imbalance_ratio(loads),
        fingerprint=_plan_fingerprint(n_shards, units, bin_of))


# --- shards ----------------------------------------------------------------


class AdmissionShard:
    """One leased scheduler instance over the shared plane. Holds its
    fencing token (named lease ``shard-<i>``), its lifecycle state and
    the admitted-counter watermark the per-shard metric feeds from."""

    def __init__(self, index: int, scheduler, token: FencingToken):
        self.index = index
        self.name = f"shard-{index}"
        self.scheduler = scheduler
        self.token = token
        self.state = SHARD_ACTIVE
        self.cycles = 0
        # Admissions by PRIOR incarnations of this slot: promote_shard
        # freezes the dead scheduler's count in here so admitted_total
        # is cumulative per shard slot, not per scheduler object.
        self.admitted_at_death = 0
        self.promotions = 0

    @property
    def epoch(self) -> int:
        return self.token.epoch

    @property
    def admitted_total(self) -> int:
        return self.admitted_at_death + self.scheduler.admitted_total

    def status(self, plan: ShardPlan, queues=None) -> dict:
        """The single producer /debug/shards, the SIGUSR2 dumper and
        tools/shard_probe.py share (the status-producer contract,
        obs/status.py)."""
        cqs = plan.cqs_of(self.index)
        pending = None
        if queues is not None:
            pending = sum(queues.pending(c) for c in cqs)
        return {
            "shard": self.name,
            "state": self.state,
            "epoch": self.epoch,
            "identity": self.token.identity,
            "lease": self.token.log.lease_status(name=self.name),
            "units": list(plan.units_of(self.index)),
            "cluster_queues": list(cqs),
            "pending_backlog": pending,
            "cycles": self.cycles,
            "admitted_total": self.admitted_total,
            "promotions": self.promotions,
        }


class ShardedControlPlane:
    """N admission shards over one shared watch/store plane. Drive it
    deterministically: ``cycle()`` runs every active shard's admission
    cycle once (round-robin, each under its own fencing token and
    faultinject scope) and settles the plane's reconcilers.

    Concurrency note: shards simulate separate processes inside one
    interpreter (the same stance as the multihost mesh harness), so
    cycles run sequentially and the ``Store.fencing`` swap per cycle
    is single-threaded by construction. The safety story does NOT rely
    on that: every fence is re-checked under the durable log's own
    lock at append time."""

    def __init__(self, n_shards: int, cfg=None, clock: Clock = REAL_CLOCK,
                 solver=None, durable=None, checkpoint_every: int = 256,
                 lease_duration: float = DEFAULT_SHARD_LEASE_S,
                 weights: Optional[dict] = None):
        from kueue_tpu.manager import KueueManager
        from kueue_tpu.sim import Store
        from kueue_tpu.sim.durable import DurableLog

        self.clock = clock
        self.lease_duration = lease_duration
        self.durable = durable if durable is not None else DurableLog(
            checkpoint_every=checkpoint_every)
        store = Store(clock)
        store.attach_durable(self.durable)
        self.plane = KueueManager(cfg=cfg, clock=clock, solver=solver,
                                  store=store)
        self.plane.durable = self.durable
        # The plane's own scheduler NEVER admits — the shards do. Pin
        # its leader gate closed (same mechanism the hot standby uses).
        self.plane.scheduler.leader_check = lambda: False
        self.metrics = self.plane.metrics
        self.log = vlog.logger("shards")
        self.n_shards = max(int(n_shards), 1)
        self.rebalances = 0
        self.plan = ShardPlan(n_shards=self.n_shards, units=(),
                              shard_of_unit={}, cq_shard={}, loads=(),
                              imbalance=1.0, fingerprint="")
        self.shards: list = []
        for i in range(self.n_shards):
            self.shards.append(self._build_shard(i))
        # Shard status on the plane's debug surface: /debug/shards and
        # the SIGUSR2 dumper read this one producer (obs/status.py).
        self.plane.scheduler.shards_status = self.status

    # -- construction ---------------------------------------------------

    def _new_scheduler(self):
        """A shard's scheduler over the SHARED queues/cache/client —
        the manager's construction recipe, minus the solver plumbing
        (shards share the plane's flight recorder and metrics; each
        gets its own pipeline/ladder/breaker state by construction)."""
        from kueue_tpu.scheduler.scheduler import Scheduler
        p = self.plane
        sched = Scheduler(
            p.queues, p.cache, p.scheduler_client,
            ordering=p.scheduler.ordering,
            fair_sharing_enabled=p.cfg.fair_sharing.enable,
            fs_preemption_strategies=(
                p.cfg.fair_sharing.preemption_strategies),
            clock=self.clock, metrics=p.metrics,
            solver_min_heads=p.cfg.solver.min_heads,
            recorder=p.flight_recorder)
        sched.journeys = p.journey_ledger
        return sched

    def _lease_shard(self, index: int) -> FencingToken:
        """Acquire shard ``index``'s named lease under a FRESH identity
        — every (re)lease bumps the epoch, which is exactly the fence:
        the previous holder's in-flight writes die at the log."""
        name = f"shard-{index}"
        identity = f"{name}-{uuid.uuid4().hex[:8]}"
        epoch = self.durable.acquire_lease(
            identity, now=self.clock.now(),
            duration=self.lease_duration, force=True, name=name)
        return FencingToken(self.durable, identity, epoch, name=name)

    def _build_shard(self, index: int) -> AdmissionShard:
        sched = self._new_scheduler()
        token = self._lease_shard(index)
        sched.fencing_check = token.valid
        sched.leader_check = token.valid
        sched.cq_filter = self._cq_filter(index)
        shard = AdmissionShard(index, sched, token)
        if self.metrics is not None:
            self.metrics.set_shard_state(shard.name, shard.state)
        return shard

    def _cq_filter(self, index: int):
        def owns(cq_name: str, _i=index) -> bool:
            # Unmapped CQs (created after the last replan) default to
            # shard 0 so no head is ever orphaned between replans.
            return self.plan.cq_shard.get(cq_name, 0) == _i
        return owns

    # -- layout ---------------------------------------------------------

    def replan(self, weights: Optional[dict] = None) -> ShardPlan:
        """(Re)compute the unit -> shard layout from the live cache
        topology. Call after seeding CQs, and at any topology change
        big enough to matter — between cycles, never during one."""
        units = shard_units(self.plane.cache)
        if weights is None:
            weights = {cq: max(self.plane.queues.pending(cq), 1)
                       for cq in units}
        self.plan = plan_shards(units, weights, self.n_shards)
        return self.plan

    # -- driving --------------------------------------------------------

    @property
    def store(self):
        return self.plane.store

    def renew_leases(self) -> None:
        """Renew every ACTIVE shard's lease at the current clock —
        the harness's heartbeat. A dead shard's lease is deliberately
        left to expire (or be force-taken at promotion)."""
        now = self.clock.now()
        for shard in self.shards:
            if shard.state == SHARD_ACTIVE:
                shard.token.renew(now)

    def shard_cycle(self, index: int, timeout: Optional[float] = 0):
        """One admission cycle of shard ``index``, under its fencing
        token and its own faultinject scope. An ``InjectedCrash``
        marks the shard killed (its in-memory state is dead — exactly
        a process death) and re-raises nothing: the shared plane is
        the surviving fault domain."""
        from kueue_tpu.resilience.faultinject import InjectedCrash
        shard = self.shards[index]
        if shard.state != SHARD_ACTIVE:
            return None
        store = self.plane.store
        prev = store.fencing
        store.fencing = shard.token
        try:
            with faultinject.scope(shard.name):
                sig = shard.scheduler.schedule(timeout=timeout)
            shard.cycles += 1
            return sig
        except InjectedCrash:
            self._mark_dead(shard, SHARD_KILLED)
            return None
        finally:
            store.fencing = prev
            if self.metrics is not None:
                self.metrics.shard_admitted(
                    shard.name,
                    shard.scheduler.admitted_total
                    - getattr(shard, "_metric_mark", 0))
                shard._metric_mark = shard.scheduler.admitted_total

    def cycle(self, settle: bool = True) -> dict:
        """One round-robin pass: every ACTIVE shard runs one admission
        cycle; the plane's reconcilers settle between shards so each
        shard sees the previous one's committed writes (the same
        ordering a real apiserver's watch stream gives co-resident
        schedulers). Returns {shard name: signal-or-None}."""
        out = {}
        for shard in list(self.shards):
            if settle:
                self.plane.run_until_idle()
            out[shard.name] = self.shard_cycle(shard.index)
        if settle:
            self.plane.run_until_idle()
        return out

    # -- fault protocol -------------------------------------------------

    def _mark_dead(self, shard: AdmissionShard, state: str) -> None:
        # The watermark is NOT advanced here: the dead scheduler stays
        # attached, so admitted_total still reads base + its count.
        # Only promote_shard freezes the dead incarnation into the base.
        shard.state = state
        self.log.v(1, "shards.dead", shard=shard.name, state=state,
                   epoch=shard.epoch)
        if self.metrics is not None:
            self.metrics.set_shard_state(shard.name, state)

    def kill_shard(self, index: int) -> None:
        """Simulate shard process death between cycles (mid-cycle
        deaths arrive as InjectedCrash through shard_cycle). The dead
        scheduler's in-flight speculation is NOT drained — a real
        SIGKILL drains nothing; un-popped heads simply stay heaped and
        popped-but-uncommitted heads re-heap at promotion."""
        shard = self.shards[index]
        if shard.state == SHARD_ACTIVE:
            self._mark_dead(shard, SHARD_KILLED)

    def promote_shard(self, index: int) -> AdmissionShard:
        """Hot-promote a replacement over shard ``index``: bump the
        named lease's epoch under a fresh identity (fencing the dead
        holder's zombie writes FIRST — the promotion ordering argument
        from RESILIENCE.md §7), then adopt the cohort set with a fresh
        scheduler in the restore() posture (first cycle pinned
        synchronous, breaker/ladder at their fresh rungs). Sub-cycle
        by construction: the plane's watch-driven state never died.

        The dead scheduler's abandoned pipeline state is reconciled
        here: heads it popped but never committed re-heap (requeue by
        key), so no workload is stranded."""
        old = self.shards[index]
        prior_cycles = old.cycles
        prior_admitted = old.admitted_total
        promotions = old.promotions + 1
        # Requeue anything the dead shard popped and never committed.
        # Its scheduler object is our window into the dead process's
        # final memory — the harness's stand-in for "the workloads the
        # store still says are pending".
        try:
            if old.scheduler._inflight_q or old.scheduler._inflight:
                old.scheduler._abandon_pipeline()
        except Exception:  # noqa: BLE001 — dead state may be torn
            pass
        # Release any snapshot handout the dead cycle still held (a
        # crash between take and retire): the shared cache's handout
        # ledger survives the shard, the aborted frame's local doesn't.
        try:
            old.scheduler._flush_seal_snapshot()
        except Exception:  # noqa: BLE001
            pass
        shard = self._build_shard(index)
        shard.cycles = prior_cycles
        shard.admitted_at_death = prior_admitted
        shard.promotions = promotions
        # Takeover posture: never a speculative first cycle over state
        # another holder touched (mirrors StandbyReplica.promote()).
        shard.scheduler._pipeline_cooldown = max(
            shard.scheduler._pipeline_cooldown, 1)
        self.shards[index] = shard
        self._resync_shard(index)
        self.log.v(1, "shards.promoted", shard=shard.name,
                   epoch=shard.epoch, promotions=promotions)
        if self.metrics is not None:
            self.metrics.shard_promoted(shard.name)
            self.metrics.set_shard_state(shard.name, shard.state)
        return shard

    def _resync_shard(self, index: int) -> None:
        """Store-driven repair after a shard death. The whole-plane
        restore path rebuilds queues/cache wholesale from the WAL; here
        the plane SURVIVES the shard, so only the dead scheduler's torn
        mid-cycle residue needs reconciling against the store — the
        durable admission records are the arbiter either way:

        - an ASSUMED cache entry with no durable admission is the
          ``apply_commit`` tear (cache counted it, the store write
          never happened): forget it — the store still says pending;
        - a pending store workload absent from its CQ heap is a
          popped-but-uncommitted head the dead cycle took with it:
          requeue it, so nothing is stranded.

        Scoped to the whole plane, not just the promoted shard's CQs:
        the repair is idempotent (requeue_if_not_present, forget only
        on divergence) and a rebalance may have moved units since the
        death."""
        from kueue_tpu.core import workload as wlpkg
        from kueue_tpu.queue.cluster_queue import RequeueReason

        store = self.plane.store
        cache = self.plane.cache
        queues = self.plane.queues
        for key, _cq in list(cache.assumed_workloads.items()):
            ns, _, name = key.partition("/")
            wl = store.try_get("Workload", ns, name)
            if wl is None or not wlpkg.has_quota_reservation(wl):
                cached = cache.hm.cluster_queues.get(_cq)
                stale = (cached.workloads.get(key).obj
                         if cached is not None
                         and key in cached.workloads else None)
                target = stale if stale is not None else wl
                if target is not None:
                    cache.forget_workload(target)
        for wl in store.list("Workload", copy_objects=False):
            if wlpkg.has_quota_reservation(wl) or not wlpkg.is_active(wl):
                continue
            info = wlpkg.Info(wl)
            info.cluster_queue = queues.cluster_queue_for_workload(wl)
            if info.cluster_queue is None:
                continue
            queues.requeue_workload(
                info, RequeueReason.FAILED_AFTER_NOMINATION)

    def rebalance(self, unit: str, to_shard: int) -> dict:
        """Planner-driven unit move under traffic. Protocol (the
        §9 rebalance contract, gated by the shard_rebalance scenario):
        (1) FENCE the old owner — re-lease its slot at a bumped epoch,
        so its in-flight speculative cycle can no longer commit stale
        admissions for the moved cohort; (2) DRAIN — the old owner's
        pipeline abandons (heads re-heap; nothing is lost because
        nothing uncommitted is kept); (3) REASSIGN the layout; (4) the
        new owner admits on its next cycle. Returns a small report."""
        if unit not in self.plan.shard_of_unit:
            raise ValueError(f"unknown unit {unit!r}")
        to_shard = int(to_shard)
        if not 0 <= to_shard < self.n_shards:
            raise ValueError(f"shard {to_shard} out of range")
        from_shard = self.plan.shard_of_unit[unit]
        if from_shard == to_shard:
            return {"unit": unit, "from": from_shard, "to": to_shard,
                    "moved": False}
        old = self.shards[from_shard]
        # (1) fence + (2) drain the old owner, then re-arm it as the
        # same shard at the new epoch (it keeps its other units).
        if old.state == SHARD_ACTIVE:
            try:
                if old.scheduler._inflight_q or old.scheduler._inflight:
                    old.scheduler._abandon_pipeline()
            except Exception:  # noqa: BLE001
                pass
            token = self._lease_shard(from_shard)
            old.token = token
            old.scheduler.fencing_check = token.valid
            old.scheduler.leader_check = token.valid
            old.scheduler._pipeline_cooldown = max(
                old.scheduler._pipeline_cooldown, 1)
        # (3) reassign: a NEW plan object (the fingerprint moves with
        # the layout — two planes comparing fingerprints agree on
        # ownership or refuse).
        shard_of_unit = dict(self.plan.shard_of_unit)
        shard_of_unit[unit] = to_shard
        cq_units = shard_units(self.plane.cache)
        cq_shard = {cq: shard_of_unit.get(u, 0)
                    for cq, u in cq_units.items()}
        units = self.plan.units
        bins = [shard_of_unit[u] for u in units]
        self.plan = ShardPlan(
            n_shards=self.n_shards, units=units,
            shard_of_unit=shard_of_unit, cq_shard=cq_shard,
            loads=self.plan.loads, imbalance=self.plan.imbalance,
            fingerprint=_plan_fingerprint(self.n_shards, units, bins))
        self.rebalances += 1
        if self.metrics is not None:
            self.metrics.shard_rebalanced()
        self.log.v(1, "shards.rebalance", unit=unit,
                   src=from_shard, dst=to_shard,
                   fingerprint=self.plan.fingerprint)
        return {"unit": unit, "from": from_shard, "to": to_shard,
                "moved": True,
                "old_owner_epoch": self.shards[from_shard].epoch}

    # -- operator surface ----------------------------------------------

    def status(self) -> dict:
        """The /debug/shards payload: layout fingerprint + per-shard
        epoch/lease/cohort set/backlog (obs/status.shards_status)."""
        return {
            "n_shards": self.n_shards,
            "plan": {
                "fingerprint": self.plan.fingerprint,
                "units": len(self.plan.units),
                "imbalance": round(self.plan.imbalance, 4),
                "loads": list(self.plan.loads),
            },
            "rebalances": self.rebalances,
            "shards": [s.status(self.plan, self.plane.queues)
                       for s in self.shards],
        }

    def shutdown(self) -> None:
        for shard in self.shards:
            try:
                if (shard.scheduler._inflight_q
                        or shard.scheduler._inflight):
                    shard.scheduler._abandon_pipeline()
            except Exception:  # noqa: BLE001
                pass
            # Dead or alive: release any snapshot handout the shard's
            # last cycle still held against the shared cache.
            try:
                shard.scheduler._flush_seal_snapshot()
            except Exception:  # noqa: BLE001
                pass
            if shard.state == SHARD_ACTIVE:
                shard.token.release()
        self.plane.shutdown(checkpoint=False)
