"""First-class conflict-domain planner: ONE layout decision.

Before this module the conflict-domain partitioning was implicit in
mesh.py's Phase B sharding (domain d -> device d mod n: naive
round-robin over a domain space that is mostly empty), the preemption
problem axis sliced contiguously, and MultiKueue remote clusters were
not part of the layout at all. The planner owns the single decision all
three consume:

- **domain -> device placement** for the sharded Phase B scan
  (mesh.solve_cycle_sharded gathers each device's planner-assigned
  grid columns instead of a modulo stride);
- **preemption problem -> device placement** (the PR-9 problem axis),
  weighted by candidate-pool size;
- **remote-cluster capacity columns** ride the same snapshot/encode
  path (solver/encode.encode_cluster_columns) so cross-cluster
  placement is scored inside the same batched program.

Partitioning is COST-BALANCED, not round-robin: a domain's weight is
``sum over its batch workloads of the CQ's flavor width`` (workload
count x flavor width — the Phase B scan cost of one grid column is one
availability walk + fit check over the CQ's flavor rows per rank).
The LPT (longest-processing-time greedy) assignment is deterministic —
ties break on domain id, then device id — so the plan fingerprint is
stable across process restarts and can key warm-ladder entries.

Only OCCUPIED domains get columns: the naive layout scanned all
C + Q domain columns per device even though a 2048-head cycle touches
at most 2048 of the 16k+ domains at the north-star shape. Padding
columns map to the EMPTY sentinel (one extra all-invalid grid column),
so duplicated pad lanes are no-ops and the psum-combined decisions stay
bit-identical to the single-chip oracle.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np


def _bucket(n: int, minimum: int = 8, factor: int = 2) -> int:
    """Power-of-`factor` bucketing for jit-shape stability (the per-
    device column count is a traced-array dim; coarse buckets keep the
    compiled-executable population small)."""
    b = minimum
    while b < n:
        b *= factor
    return b


def workload_domains(wl_cq, cq_cohort, cohort_root) -> np.ndarray:
    """[W] conflict-domain id per workload: the root cohort index, or a
    synthetic ``C + cq`` domain for cohortless CQs. The ONE definition
    of the domain mapping — kernel.build_order_grid, mesh.py and the
    planner all derive from the same rule (reference: fit/borrow math
    walks within one cohort tree, pkg/cache/resource_node.go)."""
    wl_cq = np.asarray(wl_cq)
    cq_cohort = np.asarray(cq_cohort)
    cohort_root = np.asarray(cohort_root)
    C = len(cohort_root)
    cohort_of = cq_cohort[wl_cq]
    if C == 0:  # cohortless topology: every CQ is its own domain
        return wl_cq.astype(np.int64)
    root_of = cohort_root[np.maximum(cohort_of, 0)]
    return np.where(cohort_of >= 0, root_of.astype(np.int64),
                    C + wl_cq.astype(np.int64))


def flavor_width(offered) -> np.ndarray:
    """[Q] per-CQ flavor width (>=1): the number of flavor rows a Phase B
    availability/fit evaluation touches for one of the CQ's workloads —
    the per-rank scan cost factor of the CQ's domain column."""
    offered = np.asarray(offered)
    return np.maximum(offered.any(axis=2).sum(axis=1), 1).astype(np.int64)


def balanced_partition(weights, n_bins: int):
    """Deterministic LPT greedy: items sorted by (-weight, index) land
    on the least-loaded bin (ties -> lowest bin id). Returns
    (bin_of_item [N] int32, loads [n_bins] int64). Guarantee: max load
    <= (4/3 - 1/(3*n_bins)) * optimal, vs. unbounded skew for naive
    round-robin when heavy items share a residue class."""
    import heapq
    weights = np.asarray(weights, np.int64)
    n = len(weights)
    bin_of = np.zeros(n, np.int32)
    loads = np.zeros(n_bins, np.int64)
    if n == 0 or n_bins <= 1:
        return bin_of, loads if n == 0 else _accumulate(weights, bin_of,
                                                        n_bins)
    order = np.lexsort((np.arange(n), -weights))
    heap = [(0, b) for b in range(n_bins)]  # (load, bin) — already a heap
    for i in order.tolist():
        load, b = heapq.heappop(heap)
        bin_of[i] = b
        load += int(weights[i])
        loads[b] = load
        heapq.heappush(heap, (load, b))
    return bin_of, loads


def _accumulate(weights, bin_of, n_bins):
    loads = np.zeros(n_bins, np.int64)
    np.add.at(loads, bin_of, weights)
    return loads


def round_robin_partition(weights, n_bins: int):
    """The pre-planner layout (domain d -> device d mod n), kept as the
    comparison baseline for tests and tools/mesh_probe.py."""
    weights = np.asarray(weights, np.int64)
    bin_of = (np.arange(len(weights)) % max(n_bins, 1)).astype(np.int32)
    return bin_of, _accumulate(weights, bin_of, n_bins)


def imbalance_ratio(loads) -> float:
    """max/mean over LOADED devices (1.0 = perfectly balanced). The
    mesh_probe CLI fails the run above 1.5x. Zero-load devices are
    excluded: with fewer occupied domains than devices the optimal
    layout necessarily idles some devices (LPT seeds the first
    ``min(items, bins)`` bins with distinct items, so a zero bin only
    appears in exactly that regime), and counting them would fail a
    layout that cannot be improved."""
    loads = np.asarray(loads, np.float64)
    loads = loads[loads > 0]
    if loads.size == 0:
        return 1.0
    return float(loads.max() / loads.mean())


@dataclass(frozen=True)
class DomainPlan:
    """Domain -> device layout for one cycle. ``columns[dev]`` lists the
    grid-column (domain) ids device `dev` scans, padded with -1; the
    mesh path rewrites -1 to its empty-column sentinel. The fingerprint
    is stable across processes (blake2b over the layout bytes, no
    ``hash()``/``id()``), so warm-ladder keys derived from it survive
    restarts."""

    n_devices: int
    columns: np.ndarray           # [n_devices, d_cols] int64, -1 pad
    loads: np.ndarray             # [n_devices] int64 weighted load
    occupied: int                 # distinct occupied domains
    imbalance: float
    fingerprint: str = field(default="")

    @property
    def d_cols(self) -> int:
        return int(self.columns.shape[1])


def _plan_fingerprint(n_devices: int, columns: np.ndarray) -> str:
    h = hashlib.blake2b(digest_size=8)
    h.update(np.int64(n_devices).tobytes())
    h.update(np.int64(columns.shape[1]).tobytes())
    h.update(np.ascontiguousarray(columns, np.int64).tobytes())
    return h.hexdigest()


def plan_domains(wl_cq, cq_cohort, cohort_root, offered,
                 n_devices: int, min_cols: int = 8) -> DomainPlan:
    """Cost-balanced domain -> device plan for one cycle's batch.

    wl_cq: [W] (the FULL padded batch — padding rows occupy grid slots
    and must map onto an assigned column, exactly like the fused
    single-chip grid). Weight of a domain = sum over its batch rows of
    the row's CQ flavor width.
    """
    wl_cq = np.asarray(wl_cq)
    dom = workload_domains(wl_cq, cq_cohort, cohort_root)
    D = len(np.asarray(cohort_root)) + len(np.asarray(cq_cohort))
    fw = flavor_width(offered)
    weights = np.bincount(dom, weights=fw[wl_cq].astype(np.float64),
                          minlength=D).astype(np.int64)
    occupied = np.flatnonzero(np.bincount(dom, minlength=D))
    n_devices = max(int(n_devices), 1)
    bin_of, loads = balanced_partition(weights[occupied], n_devices)
    counts = np.bincount(bin_of, minlength=n_devices) if len(occupied) \
        else np.zeros(n_devices, np.int64)
    d_cols = _bucket(max(int(counts.max()) if len(occupied) else 1, 1),
                     min_cols)
    columns = np.full((n_devices, d_cols), -1, np.int64)
    fill = np.zeros(n_devices, np.int64)
    # stable fill order (ascending domain id) — part of the fingerprint
    for d, b in zip(occupied.tolist(), bin_of.tolist()):
        columns[b, fill[b]] = d
        fill[b] += 1
    plan = DomainPlan(
        n_devices=n_devices, columns=columns, loads=loads,
        occupied=len(occupied), imbalance=imbalance_ratio(loads),
        fingerprint=_plan_fingerprint(n_devices, columns))
    return plan


def plan_problems(weights, n_devices: int, min_local: int = 1):
    """Preemption problem axis -> device placement (the PR-9 axis rides
    the same planner). Returns (perm [n_devices * b_local] int64 padded
    with N, inv [N] int64, b_local): device k's slice is
    ``perm[k*b_local:(k+1)*b_local]``; pad lanes index the one extra
    all-zero problem row the mesh path appends; ``inv`` restores the
    gathered outputs to original problem order."""
    weights = np.asarray(weights, np.int64)
    n = len(weights)
    n_devices = max(int(n_devices), 1)
    bin_of, _loads = balanced_partition(weights, n_devices)
    counts = np.bincount(bin_of, minlength=n_devices) if n else \
        np.zeros(n_devices, np.int64)
    b_local = max(int(counts.max()) if n else 0, min_local)
    perm = np.full(n_devices * b_local, n, np.int64)
    inv = np.zeros(n, np.int64)
    fill = np.zeros(n_devices, np.int64)
    for i, b in enumerate(bin_of.tolist()):
        pos = b * b_local + int(fill[b])
        perm[pos] = i
        inv[i] = pos
        fill[b] += 1
    return perm, inv, b_local
