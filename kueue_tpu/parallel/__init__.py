"""Multi-chip / multi-host execution: device meshes, the first-class
conflict-domain planner, and the cohort-parallel sharded solve
(jax.sharding + shard_map over ICI/DCN). Design doc: MESH.md.

``domains`` is import-light (numpy only) — the planner is usable from
host-side tooling without initializing a jax backend; ``mesh`` pulls in
jax on first import.

``shards`` promotes the SAME planner decision to control-plane layout
(RESILIENCE.md §9): N leased admission shards over one shared
watch/store plane, each owning a planner-assigned set of cohort
subtrees, fenced per-shard through the durable log's named leases.
"""
