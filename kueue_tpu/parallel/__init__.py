"""Multi-chip execution: device meshes and the cohort-parallel sharded
solve (jax.sharding + shard_map over ICI/DCN)."""
