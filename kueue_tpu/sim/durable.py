"""Checkpoint + WAL durability for the sim Store — the "etcd" role.

SURVEY.md §5 names the property the reference leans on for fault
tolerance: *etcd is the checkpoint, restart is cheap*. Every derived
structure (queue heaps, cache trees, snapshot masters, encode arena,
device residency) is rebuildable from the object store, so process
death costs only a replay. This module gives the sim's authoritative
Store that durable surface:

- an **append-only event log** (WAL) of committed mutations — one
  record per watch event the store fires (ADDED/MODIFIED/DELETED with
  the post-mutation object and its virtual commit time), so replay IS
  the event stream the live controllers consumed, and
- a **periodic checkpoint** — a full pickled image of the store taken
  every ``checkpoint_every`` records, after which the WAL rotates to
  a fresh **generation-stamped segment**.

Two backings behind one knob: the default is an **fsync-free
in-memory byte buffer** (tests, the crash-restart chaos suites — the
"disk" that survives a simulated process death is just this object
outliving the manager), and ``dir=...`` puts the same byte format in
real files (``checkpoint.bin`` + ``wal.log``) for cross-process use.

Record framing is length + CRC32 + pickled body. ``load()`` replays
the checkpoint plus the WAL tail and treats a short or checksum-failed
final record as a **torn write**: replay stops at the last intact
record with a counted warning (``LoadResult.torn_records``) instead of
raising — exactly the crash-mid-append case the WAL exists for.

Tail streaming (RESILIENCE.md §7): a hot-standby follower subscribes
to the log with a **rotation-aware cursor** — ``load_with_cursor()``
bootstraps a consistent (state, position) pair and ``read_tail()``
returns every record appended since, crossing segment rotations
transparently. Each checkpoint used to reopen the WAL ``"wb"`` (a
naive byte-offset tailer would read that as silent truncation); now
rotation **retires** the old segment under its generation stamp and
keeps the last ``retain_segments`` of them around, so a follower
lagging across a compaction still streams — only a follower further
behind than the retention window is told to ``resync`` (re-bootstrap
from the checkpoint), mirroring the snapshot journal's overflow
fallback in ``cache/incremental.py``.

Leader lease + fencing (RESILIENCE.md §7): the log — the one durable
medium that outlives every process — also arbitrates which process may
COMMIT to it. ``acquire_lease`` hands out monotonically increasing
**fencing epochs**; a deposed leader holding a stale epoch gets
``Fenced`` from ``append`` (and from ``Store._persist`` before it), so
its in-flight cycle can never reach the log the new leader replays.
The shared-Store HA mode (``utils/leaderelection.py``) keeps its
Lease-object election; this lease is the replicated-store mode's,
where each replica owns a store and the log is the only shared truth.

Recovery semantics on top of this layer live in
``kueue_tpu/resilience/recovery.py`` (cold restore, RESILIENCE.md §6)
and ``kueue_tpu/resilience/replica.py`` (hot standby, §7).
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.utils import vlog

_HEADER = struct.Struct("<II")  # (body length, crc32(body))

CHECKPOINT_FILE = "checkpoint.bin"
WAL_FILE = "wal.log"
# Retired segments are kept as wal.<generation>.log (file mode) or
# in-memory bytes until pruned past the retention window.
RETIRED_PREFIX = "wal."
RETIRED_SUFFIX = ".log"

# How many retired segments a rotation keeps for lagging tailers. A
# follower polling once per admission cycle stays within one segment of
# the head (checkpoint_every records >> records per cycle); the window
# exists for stalls, and past it the follower resyncs from the
# checkpoint — always safe, just not incremental.
DEFAULT_RETAIN_SEGMENTS = 4


class Fenced(RuntimeError):
    """A commit carrying a stale fencing epoch was rejected: another
    replica acquired the leader lease since this writer's. The deposed
    leader's write never reaches the WAL (and so can never be replayed
    by the new leader) — the hot-standby exactly-once guarantee's hard
    backstop (RESILIENCE.md §7)."""


def _frame(body: bytes) -> bytes:
    return _HEADER.pack(len(body), zlib.crc32(body)) + body


def _iter_records(buf: bytes):
    """Yield (record bytes, torn) pairs; a torn tail yields (None, True)
    once and stops. Complete, checksum-clean records stream through."""
    off = 0
    n = len(buf)
    while off < n:
        if n - off < _HEADER.size:
            yield None, True
            return
        length, crc = _HEADER.unpack_from(buf, off)
        body = buf[off + _HEADER.size:off + _HEADER.size + length]
        if len(body) < length or zlib.crc32(body) != crc:
            yield None, True
            return
        yield body, False
        off += _HEADER.size + length


def _unpack_record(body: bytes) -> tuple:
    """(event, kind, key, obj, t) — tolerating the pre-timestamp
    4-tuple shape for logs written before the tail-streaming surface."""
    rec = pickle.loads(body)
    if len(rec) == 4:
        event, kind, key, obj = rec
        return event, kind, key, obj, 0.0
    return rec


@dataclass(frozen=True)
class TailCursor:
    """A follower's position in the stream: which segment generation
    and the byte offset within it. Opaque to callers — only
    ``read_tail`` advances it."""
    generation: int = 0
    offset: int = 0


@dataclass
class TailBatch:
    """One ``read_tail`` result. ``records`` are (event, kind, key,
    obj, t) tuples in append order; ``cursor`` is the advanced
    position. ``resync`` True means the cursor fell behind the segment
    retention window (or a foreign log) — the caller must re-bootstrap
    via ``load_with_cursor`` and treat its local state as stale.
    ``segments_crossed`` counts rotations the read streamed across."""
    records: list = field(default_factory=list)
    cursor: TailCursor = field(default_factory=TailCursor)
    resync: bool = False
    segments_crossed: int = 0


@dataclass
class LoadResult:
    """What ``DurableLog.load()`` reconstructed: the object map in the
    Store's internal shape ({kind: {key: obj}}), the resource-version
    high-water mark, and the replay provenance the recovery report
    surfaces (RESILIENCE.md §6)."""

    objects: dict = field(default_factory=dict)
    rv: int = 0
    checkpoint_loaded: bool = False
    records_replayed: int = 0
    torn_records: int = 0
    warnings: list = field(default_factory=list)


@dataclass
class LoadParts:
    """The un-collapsed view of the newest recoverable state: the
    checkpoint image and the WAL tail as the ORIGINAL event records.
    ``resilience/recovery.py`` and the hot-standby bootstrap replay the
    records incrementally through ``Store.apply_replicated`` (the same
    path the follower's live tailing uses); ``collapse()`` folds them
    into the final object map for consumers that only want state."""

    objects: dict = field(default_factory=dict)   # checkpoint image
    rv: int = 0
    checkpoint_loaded: bool = False
    records: list = field(default_factory=list)   # (event,kind,key,obj,t)
    torn_records: int = 0
    warnings: list = field(default_factory=list)

    def collapse(self) -> LoadResult:
        res = LoadResult(
            objects={k: dict(v) for k, v in self.objects.items()},
            rv=self.rv, checkpoint_loaded=self.checkpoint_loaded,
            torn_records=self.torn_records,
            warnings=list(self.warnings))
        for event, kind, key, obj, _t in self.records:
            bucket = res.objects.setdefault(kind, {})
            if event == "DELETED":
                bucket.pop(key, None)
            else:
                bucket[key] = obj
            if obj is not None:
                rv = getattr(obj.metadata, "resource_version", 0) or 0
                res.rv = max(res.rv, rv)
            res.records_replayed += 1
        return res


class DurableLog:
    """The Store's durability sink. Thread-safe; the Store appends
    while holding its own lock, so record order always matches the
    watch-event order the live process observed."""

    def __init__(self, dir: Optional[str] = None,
                 checkpoint_every: int = 0,
                 retain_segments: int = DEFAULT_RETAIN_SEGMENTS):
        self.dir = dir
        self.checkpoint_every = checkpoint_every
        self.retain_segments = max(0, retain_segments)
        self._lock = threading.Lock()
        self.appends = 0
        self.checkpoints = 0
        self.records_since_checkpoint = 0
        # Virtual commit time of the newest appended record — the
        # follower's replication-lag-seconds reference point.
        self.last_append_t = 0.0
        # Segment generation: bumped at every checkpoint rotation and
        # stamped into tail cursors so a follower can tell compaction
        # from truncation.
        self.generation = 0
        # Leases (fencing): NAMED lease slots, each with its own holder
        # identity, monotone fencing epoch, and renew clock. Name ""
        # is the whole-plane leader lease every pre-shard caller uses;
        # shard leases ("shard-0", ...) arbitrate per-shard ownership
        # on the same durable medium (RESILIENCE.md §9). All times are
        # caller-supplied (the log has no clock of its own —
        # virtual-time harnesses pass their FakeClock readings).
        self._leases: dict = {}
        self.log = vlog.logger("durable")
        if dir is None:
            self._wal = bytearray()
            self._ckpt: Optional[bytes] = None
            self._wal_file = None
            self._retired: dict[int, bytes] = {}
        else:
            os.makedirs(dir, exist_ok=True)
            self._wal = None
            self._ckpt = None
            self._retired = None
            # A re-opened dir resumes after the newest retired segment
            # (cursors from a previous process resync past a reset).
            gens = self._retired_generations_on_disk()
            self.generation = (max(gens) + 1) if gens else 0
            # Buffered append handle, flushed per record but never
            # fsynced — the fsync-free contract; a torn tail is the
            # accepted (and handled) failure shape.
            self._wal_file = open(os.path.join(dir, WAL_FILE), "ab")
            self.records_since_checkpoint = self._count_records()

    # -- append path ---------------------------------------------------

    def append(self, event: str, kind: str, key: str, obj,
               t: float = 0.0, fence: Optional[tuple] = None) -> None:
        """One committed store mutation: ``event`` is the watch event
        type (ADDED/MODIFIED/DELETED), ``obj`` the post-mutation stored
        object (the DELETED record carries the final image so replay
        can drop finalized deletes by key), ``t`` the committing
        store's clock reading (the follower's lag-seconds basis).

        ``fence=(identity, epoch)``: the append is rejected with
        ``Fenced`` — under the log lock, atomically with the write —
        when a lease exists and the writer's epoch is stale. This is
        the medium-level backstop: a deposed leader cannot append even
        if it races the promotion between a validity check and the
        write."""
        body = pickle.dumps((event, kind, key, obj, t),
                            protocol=pickle.HIGHEST_PROTOCOL)
        rec = _frame(body)
        with self._lock:
            if fence is not None:
                self._check_epoch_locked(*fence)
            if self._wal_file is not None:
                self._wal_file.write(rec)
                self._wal_file.flush()
            else:
                self._wal += rec
            self.appends += 1
            self.records_since_checkpoint += 1
            self.last_append_t = t

    def should_checkpoint(self) -> bool:
        return (self.checkpoint_every > 0
                and self.records_since_checkpoint >= self.checkpoint_every)

    def checkpoint(self, objects: dict, rv: int,
                   fence: Optional[tuple] = None) -> None:
        """Full image ({kind: {key: obj}}, rv); the WAL **rotates**: the
        written-out segment retires under the current generation (kept
        for ``retain_segments`` rotations so lagging tailers stream
        across the compaction instead of resyncing) and a fresh segment
        opens under generation+1. The caller (Store.checkpoint_now)
        holds the store lock, so the image is a consistent cut of the
        committed state.

        ``fence=(identity, epoch)`` rejects a STALE writer's checkpoint
        with ``Fenced`` — without it a deposed leader's graceful
        shutdown would replace the checkpoint with its stale image and
        rotate away the new leader's live WAL tail: silent loss of
        every admission committed since the takeover."""
        body = pickle.dumps((objects, rv),
                            protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            if fence is not None:
                self._check_epoch_locked(*fence)
            if self.dir is not None:
                tmp = os.path.join(self.dir, CHECKPOINT_FILE + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(_frame(body))
                os.replace(tmp, os.path.join(self.dir, CHECKPOINT_FILE))
                self._wal_file.close()
                # Retire (rename, atomically) instead of truncating in
                # place: a tailer's stale handle-by-path re-opens per
                # read, and its cursor's generation tells it which
                # segment its offset belongs to.
                wal_path = os.path.join(self.dir, WAL_FILE)
                if self.retain_segments > 0:
                    os.replace(wal_path, self._retired_path(self.generation))
                else:
                    os.unlink(wal_path)
                self._wal_file = open(wal_path, "wb")
            else:
                self._ckpt = _frame(body)
                if self.retain_segments > 0:
                    self._retired[self.generation] = bytes(self._wal)
                self._wal = bytearray()
            self.generation += 1
            self._prune_retired_locked()
            self.checkpoints += 1
            self.records_since_checkpoint = 0

    # -- leases + fencing (RESILIENCE.md §7, §9) -----------------------

    def _lease_locked(self, name: str) -> dict:
        lease = self._leases.get(name)
        if lease is None:
            lease = {"holder": "", "epoch": 0, "renew_t": 0.0,
                     "duration": 0.0}
            self._leases[name] = lease
        return lease

    def acquire_lease(self, identity: str, now: float,
                      duration: float = 15.0,
                      force: bool = False,
                      name: str = "") -> Optional[int]:
        """Take (or retake) the ``name`` lease ("" = the whole-plane
        leader lease; shard leases carry the shard's name). Returns the
        fencing epoch on success, None when another holder's lease is
        still live and ``force`` is False. Every change of holder —
        including a returning holder re-acquiring after expiry — bumps
        the epoch, so a write stamped with the previous epoch is fenced
        the instant the new holder wins. A current holder calling this
        is a renewal (same epoch). ``force`` is the operator/harness
        "I know the leader is dead" path (a crash leaves the lease
        formally unexpired until ``duration`` passes)."""
        with self._lock:
            lease = self._lease_locked(name)
            if lease["holder"] == identity and lease["epoch"] > 0:
                lease["renew_t"] = now
                lease["duration"] = duration
                return lease["epoch"]
            held = (lease["holder"]
                    and now < lease["renew_t"] + lease["duration"])
            if held and not force:
                return None
            lease["holder"] = identity
            lease["epoch"] += 1
            lease["renew_t"] = now
            lease["duration"] = duration
            self.log.v(1, "durable.lease.acquired", holder=identity,
                       epoch=lease["epoch"], lease=name or "leader",
                       forced=bool(held))
            return lease["epoch"]

    def renew_lease(self, identity: str, now: float,
                    name: str = "") -> bool:
        """Extend the current holder's lease; False if this identity no
        longer holds it (it was deposed — stop committing)."""
        with self._lock:
            lease = self._lease_locked(name)
            if lease["holder"] != identity:
                return False
            lease["renew_t"] = now
            return True

    def release_lease(self, identity: str, name: str = "") -> None:
        """Voluntary hand-off (graceful shutdown): the next replica
        acquires immediately instead of waiting out the duration. The
        epoch is NOT bumped here — the successor's acquire bumps it."""
        with self._lock:
            lease = self._lease_locked(name)
            if lease["holder"] == identity:
                lease["holder"] = ""
                lease["renew_t"] = 0.0

    def lease_status(self, now: Optional[float] = None,
                     name: str = "") -> dict:
        with self._lock:
            lease = self._lease_locked(name)
            st = {"holder": lease["holder"],
                  "epoch": lease["epoch"],
                  "renew_t": lease["renew_t"],
                  "duration_s": lease["duration"]}
            if now is not None:
                st["expired"] = (not lease["holder"]
                                 or now >= lease["renew_t"]
                                 + lease["duration"])
            return st

    def lease_table(self, now: Optional[float] = None) -> dict:
        """Every named lease's status — the /debug/shards raw table."""
        with self._lock:
            names = list(self._leases)
        return {n: self.lease_status(now, name=n) for n in names}

    @property
    def fencing_epoch(self) -> int:
        with self._lock:
            return self._lease_locked("")["epoch"]

    def check_epoch(self, identity: str, epoch: int,
                    name: str = "") -> None:
        """Raise ``Fenced`` unless ``identity`` still holds the
        ``name`` lease at ``epoch`` (the Store's commit-path validity
        check)."""
        with self._lock:
            self._check_epoch_locked(identity, epoch, name)

    def _check_epoch_locked(self, identity: str, epoch: int,
                            name: str = "") -> None:
        lease = self._lease_locked(name)
        if lease["epoch"] == 0:
            return  # no lease regime in effect (standalone durability)
        if lease["holder"] != identity or lease["epoch"] != epoch:
            raise Fenced(
                f"writer {identity!r} (epoch {epoch}) fenced: "
                f"{name or 'leader'} lease held by "
                f"{lease['holder']!r} at epoch {lease['epoch']}")

    # -- tail streaming (RESILIENCE.md §7) -----------------------------

    def cursor(self) -> TailCursor:
        """The CURRENT end-of-stream position (records appended after
        this call are what ``read_tail`` will return)."""
        with self._lock:
            return TailCursor(self.generation, self._segment_size_locked())

    def load_with_cursor(self) -> tuple:
        """(LoadParts, TailCursor) captured atomically: the parts
        describe exactly the records before the cursor, so a follower
        bootstrapping from them and then tailing from the cursor sees
        every record exactly once."""
        with self._lock:
            parts = self._load_parts_locked()
            cur = TailCursor(self.generation, self._segment_size_locked())
        return parts, cur

    def read_tail(self, cursor: TailCursor,
                  max_records: int = 0) -> TailBatch:
        """Every complete record appended since ``cursor``, streaming
        across retained segment rotations. An INCOMPLETE trailing
        record (a write in flight, or a torn crash tail) is left in
        place — the cursor parks before it and the next poll retries;
        promotion's post-drain checkpoint is what finally truncates a
        genuinely torn tail (resilience/replica.py). ``max_records``
        bounds one batch (0 = unbounded)."""
        out = TailBatch(cursor=cursor)
        with self._lock:
            gen, off = cursor.generation, cursor.offset
            while True:
                size = self._segment_size_of_locked(gen)
                if size is None or off > size:
                    # Not current and not retained (the cursor fell
                    # behind the retention window / predates a process
                    # restart), or offset past the segment end (a
                    # foreign or reset log): incremental catch-up is
                    # impossible — re-bootstrap from the checkpoint.
                    out.resync = True
                    out.cursor = cursor
                    out.records.clear()
                    return out
                # O(delta): only the bytes past the cursor are read
                # (seek on files, slice in memory) — a poll never
                # re-parses the records it already applied.
                chunk = self._segment_bytes_locked(gen, off)
                for body, torn in _iter_records(chunk):
                    if torn:
                        break  # incomplete so far — park, retry later
                    out.records.append(_unpack_record(body))
                    off += _HEADER.size + len(body)
                    if max_records and len(out.records) >= max_records:
                        out.cursor = TailCursor(gen, off)
                        return out
                if gen >= self.generation:
                    out.cursor = TailCursor(gen, off)
                    return out
                # This segment was retired complete; cross into the
                # next one. (A torn mid-segment record in a RETIRED
                # segment means bytes were lost mid-stream — that
                # cursor can never make progress past it, so resync.)
                if off < size:
                    out.resync = True
                    out.cursor = cursor
                    out.records.clear()
                    return out
                gen += 1
                off = 0
                out.segments_crossed += 1

    def records_ahead(self, cursor: TailCursor) -> Optional[int]:
        """How many complete records a tailer at ``cursor`` has not yet
        read — the replication-lag-records gauge. None when the cursor
        needs a resync (lag unknowable incrementally)."""
        with self._lock:
            gen, off, n = cursor.generation, cursor.offset, 0
            while True:
                size = self._segment_size_of_locked(gen)
                if size is None or off > size:
                    return None
                for body, torn in _iter_records(
                        self._segment_bytes_locked(gen, off)):
                    if torn:
                        break
                    n += 1
                if gen >= self.generation:
                    return n
                gen += 1
                off = 0

    def _segment_size_of_locked(self, gen: int) -> Optional[int]:
        if gen == self.generation:
            return self._segment_size_locked()
        if self.dir is None:
            seg = self._retired.get(gen)
            return None if seg is None else len(seg)
        path = self._retired_path(gen)
        if not os.path.exists(path):
            return None
        return os.path.getsize(path)

    def _segment_bytes_locked(self, gen: int,
                              off: int = 0) -> Optional[bytes]:
        """Segment ``gen``'s bytes from ``off`` to its end (None when
        the segment is gone)."""
        if gen == self.generation:
            if self.dir is None:
                return bytes(self._wal[off:])
            self._wal_file.flush()
            with open(os.path.join(self.dir, WAL_FILE), "rb") as f:
                if off:
                    f.seek(off)
                return f.read()
        if self.dir is None:
            seg = self._retired.get(gen)
            return None if seg is None else bytes(seg[off:])
        path = self._retired_path(gen)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            if off:
                f.seek(off)
            return f.read()

    def _segment_size_locked(self) -> int:
        if self.dir is None:
            return len(self._wal)
        self._wal_file.flush()
        return os.path.getsize(os.path.join(self.dir, WAL_FILE))

    def _retired_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"{RETIRED_PREFIX}{gen}{RETIRED_SUFFIX}")

    def _retired_generations_on_disk(self) -> list:
        gens = []
        for name in os.listdir(self.dir):
            if (name.startswith(RETIRED_PREFIX)
                    and name.endswith(RETIRED_SUFFIX)):
                mid = name[len(RETIRED_PREFIX):-len(RETIRED_SUFFIX)]
                if mid.isdigit():
                    gens.append(int(mid))
        return gens

    def _prune_retired_locked(self) -> None:
        floor = self.generation - self.retain_segments
        if self.dir is None:
            for gen in [g for g in self._retired if g < floor]:
                del self._retired[gen]
            return
        for gen in self._retired_generations_on_disk():
            if gen < floor:
                try:
                    os.unlink(self._retired_path(gen))
                except OSError:
                    pass

    # -- load path -----------------------------------------------------

    def load(self) -> LoadResult:
        """Reconstruct the newest recoverable state: checkpoint (when
        one exists) + every intact WAL record after it, collapsed into
        the final object map. A torn final record falls back to the
        state up to the last intact one, with a counted warning —
        never an exception; losing the in-flight tail write is the
        crash the log is FOR."""
        return self.load_parts().collapse()

    def load_parts(self) -> LoadParts:
        """The un-collapsed load: checkpoint image + the tail's
        original event records (see LoadParts)."""
        with self._lock:
            return self._load_parts_locked()

    def _load_parts_locked(self) -> LoadParts:
        res = LoadParts()
        ckpt = self._read_checkpoint()
        wal = self._segment_bytes_locked(self.generation)
        if ckpt is not None:
            body, torn = next(_iter_records(ckpt), (None, False))
            if body is not None:
                objects, rv = pickle.loads(body)
                res.objects = {k: dict(v) for k, v in objects.items()}
                res.rv = rv
                res.checkpoint_loaded = True
            elif torn:
                # A torn CHECKPOINT (crash mid-compaction before the
                # atomic replace — only reachable in memory mode) is
                # unrecoverable state loss for everything before it;
                # surface loudly but still replay the WAL tail.
                res.torn_records += 1
                res.warnings.append("checkpoint torn; replaying WAL only")
        for body, torn in _iter_records(bytes(wal)):
            if torn:
                res.torn_records += 1
                res.warnings.append(
                    "torn WAL tail record dropped (crash mid-append); "
                    "recovered to the last intact record")
                self.log.v(1, "durable.tornTail",
                           records=len(res.records))
                break
            rec = _unpack_record(body)
            res.records.append(rec)
            obj = rec[3]
            if obj is not None:
                rv = getattr(obj.metadata, "resource_version", 0) or 0
                res.rv = max(res.rv, rv)
        return res

    def _read_checkpoint(self) -> Optional[bytes]:
        if self.dir is None:
            return self._ckpt
        path = os.path.join(self.dir, CHECKPOINT_FILE)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def _read_wal(self) -> bytes:
        if self.dir is None:
            return bytes(self._wal)
        self._wal_file.flush()
        with open(os.path.join(self.dir, WAL_FILE), "rb") as f:
            return f.read()

    def _count_records(self) -> int:
        n = 0
        for _body, torn in _iter_records(self._read_wal()):
            if torn:
                break
            n += 1
        return n

    # -- test helpers ----------------------------------------------------

    def clone(self) -> "DurableLog":
        """A deep, independent copy of a MEMORY-backed log's durable
        state (checkpoint + retired segments + current WAL + counters;
        lease state excluded — the clone is an alternate timeline a
        bench A/B restores from, not a lease participant). File-backed
        logs are cross-process artifacts; copy the directory instead."""
        if self.dir is not None:
            raise ValueError("clone() supports memory-backed logs only")
        with self._lock:
            other = DurableLog(checkpoint_every=self.checkpoint_every,
                               retain_segments=self.retain_segments)
            other._wal = bytearray(self._wal)
            other._ckpt = self._ckpt
            other._retired = dict(self._retired)
            other.generation = self.generation
            other.appends = self.appends
            other.checkpoints = self.checkpoints
            other.records_since_checkpoint = self.records_since_checkpoint
            other.last_append_t = self.last_append_t
            return other

    def truncate_tail(self, nbytes: int) -> None:
        """Simulate a torn write: chop ``nbytes`` off the WAL tail (the
        bytes a crashed process never finished flushing)."""
        with self._lock:
            if self.dir is None:
                del self._wal[max(0, len(self._wal) - nbytes):]
                return
            self._wal_file.flush()
            path = os.path.join(self.dir, WAL_FILE)
            size = os.path.getsize(path)
            with open(path, "ab") as f:
                f.truncate(max(0, size - nbytes))

    def wal_size(self) -> int:
        with self._lock:
            if self.dir is None:
                return len(self._wal)
            self._wal_file.flush()
            return os.path.getsize(os.path.join(self.dir, WAL_FILE))

    def status(self) -> dict:
        return {
            "dir": self.dir or "memory",
            "appends": self.appends,
            "checkpoints": self.checkpoints,
            "records_since_checkpoint": self.records_since_checkpoint,
            "checkpoint_every": self.checkpoint_every,
            "wal_bytes": self.wal_size(),
            "generation": self.generation,
            "retain_segments": self.retain_segments,
            "lease": self.lease_status(),
        }
