"""Checkpoint + WAL durability for the sim Store — the "etcd" role.

SURVEY.md §5 names the property the reference leans on for fault
tolerance: *etcd is the checkpoint, restart is cheap*. Every derived
structure (queue heaps, cache trees, snapshot masters, encode arena,
device residency) is rebuildable from the object store, so process
death costs only a replay. This module gives the sim's authoritative
Store that durable surface:

- an **append-only event log** (WAL) of committed mutations — one
  record per watch event the store fires (ADDED/MODIFIED/DELETED with
  the post-mutation object), so replay IS the event stream the live
  controllers consumed, and
- a **periodic checkpoint** — a full pickled image of the store taken
  every ``checkpoint_every`` records (and on demand), after which the
  WAL restarts empty.

Two backings behind one knob: the default is an **fsync-free
in-memory byte buffer** (tests, the crash-restart chaos suites — the
"disk" that survives a simulated process death is just this object
outliving the manager), and ``dir=...`` puts the same byte format in
real files (``checkpoint.bin`` + ``wal.log``) for cross-process use.

Record framing is length + CRC32 + pickled body. ``load()`` replays
the checkpoint plus the WAL tail and treats a short or checksum-failed
final record as a **torn write**: replay stops at the last intact
record with a counted warning (``LoadResult.torn_records``) instead of
raising — exactly the crash-mid-append case the WAL exists for.
Recovery semantics on top of this layer live in
``kueue_tpu/resilience/recovery.py`` (RESILIENCE.md §6).
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.utils import vlog

_HEADER = struct.Struct("<II")  # (body length, crc32(body))

CHECKPOINT_FILE = "checkpoint.bin"
WAL_FILE = "wal.log"


def _frame(body: bytes) -> bytes:
    return _HEADER.pack(len(body), zlib.crc32(body)) + body


def _iter_records(buf: bytes):
    """Yield (record bytes, torn) pairs; a torn tail yields (None, True)
    once and stops. Complete, checksum-clean records stream through."""
    off = 0
    n = len(buf)
    while off < n:
        if n - off < _HEADER.size:
            yield None, True
            return
        length, crc = _HEADER.unpack_from(buf, off)
        body = buf[off + _HEADER.size:off + _HEADER.size + length]
        if len(body) < length or zlib.crc32(body) != crc:
            yield None, True
            return
        yield body, False
        off += _HEADER.size + length


@dataclass
class LoadResult:
    """What ``DurableLog.load()`` reconstructed: the object map in the
    Store's internal shape ({kind: {key: obj}}), the resource-version
    high-water mark, and the replay provenance the recovery report
    surfaces (RESILIENCE.md §6)."""

    objects: dict = field(default_factory=dict)
    rv: int = 0
    checkpoint_loaded: bool = False
    records_replayed: int = 0
    torn_records: int = 0
    warnings: list = field(default_factory=list)


class DurableLog:
    """The Store's durability sink. Thread-safe; the Store appends
    while holding its own lock, so record order always matches the
    watch-event order the live process observed."""

    def __init__(self, dir: Optional[str] = None,
                 checkpoint_every: int = 0):
        self.dir = dir
        self.checkpoint_every = checkpoint_every
        self._lock = threading.Lock()
        self.appends = 0
        self.checkpoints = 0
        self.records_since_checkpoint = 0
        self.log = vlog.logger("durable")
        if dir is None:
            self._wal = bytearray()
            self._ckpt: Optional[bytes] = None
            self._wal_file = None
        else:
            os.makedirs(dir, exist_ok=True)
            self._wal = None
            self._ckpt = None
            # Buffered append handle, flushed per record but never
            # fsynced — the fsync-free contract; a torn tail is the
            # accepted (and handled) failure shape.
            self._wal_file = open(os.path.join(dir, WAL_FILE), "ab")
            self.records_since_checkpoint = self._count_records()

    # -- append path ---------------------------------------------------

    def append(self, event: str, kind: str, key: str, obj) -> None:
        """One committed store mutation: ``event`` is the watch event
        type (ADDED/MODIFIED/DELETED), ``obj`` the post-mutation stored
        object (the DELETED record carries the final image so replay
        can drop finalized deletes by key)."""
        body = pickle.dumps((event, kind, key, obj),
                            protocol=pickle.HIGHEST_PROTOCOL)
        rec = _frame(body)
        with self._lock:
            if self._wal_file is not None:
                self._wal_file.write(rec)
                self._wal_file.flush()
            else:
                self._wal += rec
            self.appends += 1
            self.records_since_checkpoint += 1

    def should_checkpoint(self) -> bool:
        return (self.checkpoint_every > 0
                and self.records_since_checkpoint >= self.checkpoint_every)

    def checkpoint(self, objects: dict, rv: int) -> None:
        """Full image ({kind: {key: obj}}, rv); the WAL restarts empty.
        The caller (Store.checkpoint_now) holds the store lock, so the
        image is a consistent cut of the committed state."""
        body = pickle.dumps((objects, rv),
                            protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            if self.dir is not None:
                tmp = os.path.join(self.dir, CHECKPOINT_FILE + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(_frame(body))
                os.replace(tmp, os.path.join(self.dir, CHECKPOINT_FILE))
                self._wal_file.close()
                self._wal_file = open(
                    os.path.join(self.dir, WAL_FILE), "wb")
            else:
                self._ckpt = _frame(body)
                self._wal = bytearray()
            self.checkpoints += 1
            self.records_since_checkpoint = 0

    # -- load path -----------------------------------------------------

    def load(self) -> LoadResult:
        """Reconstruct the newest recoverable state: checkpoint (when
        one exists) + every intact WAL record after it. A torn final
        record falls back to the state up to the last intact one, with
        a counted warning — never an exception; losing the in-flight
        tail write is the crash the log is FOR."""
        res = LoadResult()
        with self._lock:
            ckpt = self._read_checkpoint()
            wal = self._read_wal()
        if ckpt is not None:
            body, torn = next(_iter_records(ckpt), (None, False))
            if body is not None:
                objects, rv = pickle.loads(body)
                res.objects = {k: dict(v) for k, v in objects.items()}
                res.rv = rv
                res.checkpoint_loaded = True
            elif torn:
                # A torn CHECKPOINT (crash mid-compaction before the
                # atomic replace — only reachable in memory mode) is
                # unrecoverable state loss for everything before it;
                # surface loudly but still replay the WAL tail.
                res.torn_records += 1
                res.warnings.append("checkpoint torn; replaying WAL only")
        for body, torn in _iter_records(bytes(wal)):
            if torn:
                res.torn_records += 1
                res.warnings.append(
                    "torn WAL tail record dropped (crash mid-append); "
                    "recovered to the last intact record")
                self.log.v(1, "durable.tornTail",
                           records=res.records_replayed)
                break
            event, kind, key, obj = pickle.loads(body)
            bucket = res.objects.setdefault(kind, {})
            if event == "DELETED":
                bucket.pop(key, None)
            else:
                bucket[key] = obj
            if obj is not None:
                rv = getattr(obj.metadata, "resource_version", 0) or 0
                res.rv = max(res.rv, rv)
            res.records_replayed += 1
        return res

    def _read_checkpoint(self) -> Optional[bytes]:
        if self.dir is None:
            return self._ckpt
        path = os.path.join(self.dir, CHECKPOINT_FILE)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def _read_wal(self) -> bytes:
        if self.dir is None:
            return bytes(self._wal)
        self._wal_file.flush()
        with open(os.path.join(self.dir, WAL_FILE), "rb") as f:
            return f.read()

    def _count_records(self) -> int:
        n = 0
        for _body, torn in _iter_records(self._read_wal()):
            if torn:
                break
            n += 1
        return n

    # -- test helpers ----------------------------------------------------

    def truncate_tail(self, nbytes: int) -> None:
        """Simulate a torn write: chop ``nbytes`` off the WAL tail (the
        bytes a crashed process never finished flushing)."""
        with self._lock:
            if self.dir is None:
                del self._wal[max(0, len(self._wal) - nbytes):]
                return
            self._wal_file.flush()
            path = os.path.join(self.dir, WAL_FILE)
            size = os.path.getsize(path)
            with open(path, "ab") as f:
                f.truncate(max(0, size - nbytes))

    def wal_size(self) -> int:
        with self._lock:
            if self.dir is None:
                return len(self._wal)
            self._wal_file.flush()
            return os.path.getsize(os.path.join(self.dir, WAL_FILE))

    def status(self) -> dict:
        return {
            "dir": self.dir or "memory",
            "appends": self.appends,
            "checkpoints": self.checkpoints,
            "records_since_checkpoint": self.records_since_checkpoint,
            "checkpoint_every": self.checkpoint_every,
            "wal_bytes": self.wal_size(),
        }
