"""Adversarial traffic search over the soak parameter surface: find
the failure modes no scripted storm triggers (ISSUE 18 tentpole b).

The scripted catalog and the composed soak (sim/soak.py) replay storm
shapes a human thought of. The weaknesses that survive those are the
ones only an unanticipated SHAPE exposes — a readiness outage two
beats longer than the backoff ramp, a burst harmonic that lands on the
churn cadence, a kill window that catches the WAL mid-checkpoint. This
module hunts for them mechanically:

- ``DIMENSIONS`` is the mutable traffic surface — every SoakParams
  knob that describes TRAFFIC (arrival mix, burst harmonics, churn
  cadence, outage geometry, readiness-storm shape, kill-site windows),
  with its legal range. Config under test (backoff bounds, readiness
  timeout, cluster shape, horizon) is deliberately NOT mutable: the
  search varies the weather, never the system.
- ``search()`` draws ``budget`` seeded mutants of a base schedule,
  runs each through the full soak gate, and keeps the probes whose
  violations are INTERESTING (SLO/invariant breaches, not harness
  artifacts of sparse mutated traffic).
- ``shrink()`` minimizes the first failing probe the way crash_run's
  --sweep narrows a kill site, generalized to traffic shapes: revert
  every mutated dimension back to the base schedule while the verdict
  stays red (ddmin over dimensions), then halve the survivors toward
  base (numeric bisection) — the result is the MINIMAL perturbation
  that still breaks the gate, which is the bug report.
- ``to_spec()/register_repro()`` serialize the minimum as a named
  scenario spec ``{"scenario", "seed", "params"}`` and install it in
  the sim/scenarios.py catalog, so ``scenario_run <name>`` replays the
  red trace forever (the repro corpus workflow, RESILIENCE.md §8).

Everything is deterministic per (base, seed): mutation draws come from
one seeded RNG, every probe replays the SAME run seed (variation comes
from the params, so a found trace is (params, seed)-replayable), and
the shrink re-runs the same runner.

``weak_backoff_fixture()`` is the planted weakness the acceptance test
hunts: a requeue backoff whose cap truncates the exponential ramp at
~2 s, so a long-enough readiness outage makes every storm victim lap
eviction -> requeue -> re-admission at line rate (amplification grows
linearly with the outage) where the healthy default's doubling ramp
keeps the lap count logarithmic.

``preempt_shape_report()`` is the warm-ladder feed (satellite 2):
adversarially-synthesized preempt-storm geometries emit their
``(B, rank)`` bucket keys — B = the bucketed problem count, rank = the
bucketed candidate-axis size, the two dims warmgov.preempt_shape_ladder
rungs on — and the report lists the keys the current ladder would NOT
precompile, i.e. the storm shapes that would cost a counted
mid-traffic compile today. ``tools/soak_run.py --shapes`` serves it.
"""

from __future__ import annotations

import random
from dataclasses import replace

from kueue_tpu.sim.soak import SoakParams, run_soak

# field -> (lo, hi, kind). The TRAFFIC surface only — see module doc.
DIMENSIONS = {
    "base_rate":           (0.01, 0.25, "float"),
    "amplitude":           (0.0, 1.0, "float"),
    "burst_extra":         (0.0, 0.6, "float"),
    "burst_width_frac":    (0.01, 0.25, "float"),
    "trickle_interval_s":  (10.0, 120.0, "float"),
    "churn_interval_frac": (0.02, 0.3, "float"),
    "outage_start_frac":   (0.05, 0.5, "float"),
    "outage_end_frac":     (0.5, 0.95, "float"),
    "storm_per_tenant":    (0, 24, "int"),
    "storm_width_s":       (1.0, 30.0, "float"),
    "storm_runtime_s":     (20.0, 240.0, "float"),
    "pods_ready_outage_s": (0.0, 180.0, "float"),
    "kill_hit_lo":         (1, 8, "int"),
    "kill_hit_hi":         (8, 60, "int"),
}

DEFAULT_MUTATION_RATE = 0.35

# Harness artifacts of sparse mutated traffic, not weaknesses: a
# mutant whose storm is too thin to reach the armed kill hit count
# simply never crashes — that's the schedule failing to fire, not the
# control plane failing to survive.
_STRUCTURAL_MARKERS = ("mis-armed",)


def interesting(violations: list) -> list:
    """The violations a probe counts for: everything except the
    harness's own structural checks (see _STRUCTURAL_MARKERS)."""
    return [v for v in violations
            if not any(m in v for m in _STRUCTURAL_MARKERS)]


def _draw(rng: random.Random, lo, hi, kind):
    """One dimension draw, boundary-biased the way fuzzers weight
    interesting values: range extremes expose dose-response failures
    (the longest outage, the widest storm) that a uniform draw rarely
    lands on, while the uniform bulk still explores the interior."""
    r = rng.random()
    if r < 0.25:
        return hi
    if r < 0.35:
        return lo
    return rng.randint(lo, hi) if kind == "int" else rng.uniform(lo, hi)


def mutate(base: SoakParams, rng: random.Random,
           rate: float = DEFAULT_MUTATION_RATE) -> SoakParams:
    """One seeded mutant: each traffic dimension independently redrawn
    (boundary-biased) with probability ``rate`` (at least one always
    moves), then clamped to the cross-dimension constraints the
    schedule needs (kill window ordered, outage start < end)."""
    changes = {}
    names = list(DIMENSIONS)
    while not changes:
        for name in names:
            if rng.random() >= rate:
                continue
            changes[name] = _draw(rng, *DIMENSIONS[name])
    cand = replace(base, **changes)
    if cand.kill_hit_hi < cand.kill_hit_lo:
        cand = replace(cand, kill_hit_hi=cand.kill_hit_lo)
    if cand.outage_end_frac <= cand.outage_start_frac:
        cand = replace(cand,
                       outage_end_frac=min(0.95,
                                           cand.outage_start_frac + 0.2))
    # Fair-play feasibility clamp: the storm's offered work per tenant
    # (count x runtime, in quota-unit-seconds) must be drainable well
    # inside the p99 bounds, or every big-enough storm trivially reds
    # the TTA gates by capacity arithmetic alone and buries the
    # control-plane weaknesses the search exists to find. Half a day
    # of the tenant's full quota is the envelope.
    cap = 0.5 * cand.day_s * cand.quota_units
    if cand.storm_per_tenant * cand.storm_runtime_s > cap:
        cand = replace(
            cand, storm_runtime_s=cap / cand.storm_per_tenant)
    return cand


def weak_backoff_fixture(base: SoakParams = None) -> SoakParams:
    """The planted weakness (acceptance fixture): an aggressive
    readiness timeout paired with a backoff cap that truncates the
    exponential ramp at ~2 s. Under a readiness outage every victim
    laps at ~(timeout + cap) seconds — amplification linear in the
    outage length — where the healthy default's doubling ramp keeps
    the lap count logarithmic and the soak's amplification bound
    holds."""
    base = base or SoakParams()
    return replace(base, pods_ready_timeout_s=5.0,
                   backoff_base_s=1.0, backoff_max_s=2.0)


def to_spec(name: str, params: SoakParams, seed: int) -> dict:
    """The serializable repro: everything a red trace needs to replay
    — the schedule params (which carry the config under test too) and
    the run seed."""
    return {"scenario": name, "seed": seed, "params": params.to_dict()}


def from_spec(spec: dict):
    """(name, seed, SoakParams) from a ``to_spec`` dict; rejects
    malformed specs loudly (unknown params keys raise)."""
    return (spec["scenario"], int(spec["seed"]),
            SoakParams.from_dict(spec["params"]))


def register_repro(spec: dict) -> str:
    """Install a repro spec as a named catalog scenario so
    ``scenario_run <name>`` (and the soak corpus workflow) replays it.
    The closure pins the recorded params; seed/scale follow the
    catalog's call convention but default to the recorded seed."""
    from kueue_tpu.sim import scenarios
    name, rec_seed, params = from_spec(spec)

    def _replay(seed: int = rec_seed, scale: str = "repro",
                _p: SoakParams = params):
        return run_soak(_p, seed=seed, scale=scale)

    scenarios.SCENARIOS[name] = _replay
    return name


def search(base: SoakParams, seed: int = 0, budget: int = 12,
           runner=run_soak, scale: str = "hunt",
           shrink_budget: int = 48) -> dict:
    """The hunt: probe 0 replays the base schedule (a red base means
    the config is broken without adversarial help — reported as such),
    then ``budget`` seeded mutants run the full soak gate at the SAME
    run seed. The first interesting failure is shrunk to its minimal
    perturbation and serialized as a repro spec. ``runner`` is
    injectable (tests stub it; --shapes never runs one).

    Returns ``{"seed", "budget", "evals", "probes": [...],
    "findings": [...], "repro": spec|None, "shrink": {...}|None}``."""
    rng = random.Random(seed ^ 0xAD5A)
    probes, findings = [], []
    evals = 0
    for i in range(budget + 1):
        cand = base if i == 0 else mutate(base, rng)
        res = runner(cand, seed=seed, scale=scale)
        evals += 1
        bad = interesting(list(res.violations))
        delta = {k: v for k, v in cand.to_dict().items()
                 if v != getattr(base, k)
                 and not isinstance(getattr(base, k), tuple)}
        probes.append({"probe": i, "base": i == 0, "delta": delta,
                       "violations": bad})
        if bad:
            findings.append({"probe": i, "params": cand.to_dict(),
                             "violations": bad})
    report = {"seed": seed, "budget": budget, "evals": evals,
              "probes": probes, "findings": findings,
              "repro": None, "shrink": None}
    # Shrink the first ADVERSARIAL finding (a red base needs no
    # minimizing — the base schedule is already the repro).
    first = next((f for f in findings if f["probe"] > 0), None)
    if first is not None:
        cand = SoakParams.from_dict(first["params"])
        mini, viols, used = shrink(cand, base, seed=seed, runner=runner,
                                   scale=scale, budget=shrink_budget)
        evals += used
        report["evals"] = evals
        report["shrink"] = {
            "from_probe": first["probe"], "evals": used,
            "violations": viols,
            "delta": {k: v for k, v in mini.to_dict().items()
                      if v != getattr(base, k)
                      and not isinstance(getattr(base, k), tuple)}}
        report["repro"] = to_spec(f"soak_repro_s{seed}", mini, seed)
    return report


def shrink(cand: SoakParams, base: SoakParams, seed: int = 0,
           runner=run_soak, scale: str = "shrink", budget: int = 48):
    """Minimize a failing schedule: (1) ddmin over dimensions — revert
    each mutated dimension to its base value, keep the revert whenever
    the gate stays red, repeat until a full pass makes no progress;
    (2) bisect the survivors — halve each remaining dimension's
    distance to base while still red. Returns ``(params, violations,
    evals)`` where ``violations`` is the minimum's interesting set.
    Budget caps total runner calls; on exhaustion the best-so-far
    minimum is returned (still failing by construction)."""
    evals = 0
    viols = None

    def still_red(p: SoakParams):
        nonlocal evals, viols
        if evals >= budget:
            return False
        res = runner(p, seed=seed, scale=scale)
        evals += 1
        bad = interesting(list(res.violations))
        if bad:
            viols = bad
        return bool(bad)

    # the entry candidate is known red; re-establish its violation set
    # under THIS runner so the returned violations are the minimum's
    if not still_red(cand):
        return cand, [], evals

    # pass 1: dimension-wise revert-to-base until a fixpoint
    progress = True
    while progress and evals < budget:
        progress = False
        for name in DIMENSIONS:
            if getattr(cand, name) == getattr(base, name):
                continue
            trial = replace(cand, **{name: getattr(base, name)})
            if still_red(trial):
                cand = trial
                progress = True

    # pass 2: bisect the surviving dimensions toward base. A true
    # interval bisection — the base value is the known-green side,
    # the candidate value the known-red side; a green midpoint moves
    # the green bound up rather than ending the search, so the
    # survivor converges to just past the failure threshold instead
    # of stalling at the first green halving.
    for name in DIMENSIONS:
        _, _, kind = DIMENSIONS[name]
        red, green = getattr(cand, name), getattr(base, name)
        if red == green:
            continue
        for _ in range(6):
            if evals >= budget:
                break
            mid = (red + green) / 2.0
            if kind == "int":
                mid = int(round(mid))
                if mid in (red, green):
                    break
            elif abs(red - mid) < 1e-3 * max(1.0, abs(red)):
                break
            if still_red(replace(cand, **{name: mid})):
                red = mid
            else:
                green = mid
        cand = replace(cand, **{name: red})
    return cand, list(viols or []), evals


# -- warm-ladder feed (satellite 2) ------------------------------------

def preempt_shape_report(base: SoakParams = None, seed: int = 0,
                         samples: int = 32) -> dict:
    """Synthesize adversarial preempt-storm geometries (no soak runs —
    pure shape arithmetic) and bucket each the way the solver would:
    ``B`` = encode._bucket(problem count, 1) (a synchronized storm
    makes ~one preemption problem per head), ``rank`` =
    encode._bucket(max(8, 4 * cohort members)) (the candidate axis K).
    Compare against the (B, K) pairs warmgov.preempt_shape_ladder
    precompiles for the harness topology at each sampled backlog: keys
    OFF the ladder are the storm shapes that would cost a counted
    mid-traffic compile today — the rung-tuning feed."""
    from kueue_tpu.solver.encode import _bucket
    from kueue_tpu.solver.warmgov import preempt_shape_ladder

    base = base or SoakParams()
    rng = random.Random(seed ^ 0x5AFE)
    # harness topology: cohorts=1, so one cohort holds every tenant CQ
    members = {"cohort-0": base.tenants}
    # The baseline is the ladder the DEPLOYED governor precompiles: the
    # base topology at the base storm width. Comparing each mutated
    # sample against a ladder recomputed at its own width would
    # self-cover by construction (B buckets by problem count — the
    # full-backlog rung always matches) and report nothing off-ladder.
    base_problems = max(1, base.tenants * max(0, base.storm_per_tenant))
    ladder_keys = {f"B{s['B']}xK{s['K']}"
                   for s in preempt_shape_ladder(members,
                                                 width=base_problems)}
    keys: dict = {}
    for _ in range(max(1, samples)):
        p = mutate(base, rng)
        per = max(0, p.storm_per_tenant)
        if per == 0:
            continue
        problems = p.tenants * per
        b = _bucket(problems, 1)
        rank = _bucket(max(8, 4 * p.tenants))
        key = f"B{b}xK{rank}"
        keys[key] = keys.get(key, 0) + 1
    off = {k: n for k, n in keys.items() if k not in ladder_keys}
    return {
        "seed": seed, "samples": samples,
        "topology": {"tenants": base.tenants, "cohorts": 1},
        "keys": dict(sorted(keys.items(), key=lambda kv: -kv[1])),
        "ladder_keys": sorted(ladder_keys),
        "off_ladder": dict(sorted(off.items(), key=lambda kv: -kv[1])),
        "suggested_rungs": sorted(off, key=lambda k: -off[k]),
    }
