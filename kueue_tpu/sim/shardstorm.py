"""Sharded-control-plane scenarios (RESILIENCE.md §9, ISSUE 20).

Two catalog scenarios over ``parallel/shards.ShardedControlPlane`` —
N leased admission shards on one shared watch/store plane — driven on
the FakeClock with seeded storms, same contract as every other entry
in ``sim/scenarios.SCENARIOS``:

- ``shard_storm``: steady per-CQ traffic while shards are killed at
  seeded points — half cleanly between cycles, half by an
  ``InjectedCrash`` scripted into the victim's OWN faultinject scope
  (co-resident shards' schedules stay untouched — the satellite-1
  isolation property) — and hot-promoted. Gates: every submitted
  workload admitted after the drain (zero lost, zero stranded), the
  store-vs-cache usage cross-check (zero cross-shard double
  admission), every shard slot's lease epoch = 1 + its promotions
  (no fencing hole), and the survivors' admission counters strictly
  growing through every outage (fault isolation, not just recovery).

- ``shard_rebalance``: the planner moves a cohort unit between shards
  mid-storm (fence old owner -> drain -> reassign -> new owner
  admits). Gates: zero double admission, the OLD owner admits nothing
  from the moved unit after the fence, the NEW owner's first
  admission for it lands within a bounded number of cycles (TTFA),
  and everything submitted is admitted after the drain.

Results are ``ScenarioResult`` rows so scenario_run / soak replay
treat them like any built-in scenario.
"""

from __future__ import annotations

import random

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.resilience import faultinject
from kueue_tpu.resilience.faultinject import CRASH, FaultInjector
from kueue_tpu.sim.scenarios import (ScenarioResult, SLOSpec,
                                     _backend_info, _usage_consistent)

MAX_TTFA_CYCLES = 3   # rebalance: new owner must admit within this


def _objects(num_cqs: int, quota: int):
    # Half as many cohorts as CQs so every shard in a
    # shards == num_cqs/2 layout owns at least one unit (units are
    # cohort-level — see parallel/domains.py).
    n_cohorts = max(2, num_cqs // 2)
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(num_cqs):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % n_cohorts}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=quota)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def _workload(wave: int, i: int, n: int):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{wave}-{i}", namespace="default", uid=f"wl-{wave}-{i}",
        creation_timestamp=float(n)))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 1000})]))))
    return wl


def _admitted(plane) -> int:
    return sum(1 for wl in plane.store.list("Workload",
                                            copy_objects=False)
               if wlpkg.has_quota_reservation(wl))


def _build_plane(n_shards: int, num_cqs: int, quota: int):
    from kueue_tpu.parallel.shards import ShardedControlPlane
    clock = FakeClock(1000.0)
    scp = ShardedControlPlane(n_shards, clock=clock,
                              checkpoint_every=128)
    for obj in _objects(num_cqs, quota):
        scp.plane.store.create(obj)
    scp.plane.run_until_idle(max_iterations=1_000_000)
    scp.replan()
    return scp, clock


def run_shard_storm(seed: int = 0, scale: str = "full") -> ScenarioResult:
    from kueue_tpu.parallel.shards import SHARD_ACTIVE

    p = {"smoke": dict(waves=6, cqs=4, shards=2, kills=2),
         "full": dict(waves=20, cqs=8, shards=4, kills=6),
         }[scale]
    # Quota sized so the whole storm fits: zero-lost is then exact —
    # any un-admitted workload after the drain is a stranding bug, not
    # a capacity artifact.
    scp, clock = _build_plane(p["shards"], p["cqs"],
                              quota=1000 * (p["waves"] + 1))
    rng = random.Random(seed ^ 0x5A4D)
    kill_waves = sorted(rng.sample(range(1, p["waves"] - 1), p["kills"])
                        if p["waves"] - 2 >= p["kills"] else [])
    res = ScenarioResult(name="shard_storm", seed=seed, scale=scale,
                         backend=_backend_info())
    res.slo = SLOSpec(min_admitted=p["waves"] * p["cqs"])
    survivor_stalls = 0
    n = 0
    mid_cycle_kills = 0
    try:
        for wave in range(p["waves"]):
            for i in range(p["cqs"]):
                scp.plane.store.create(_workload(wave, i, n))
                n += 1
            scp.plane.run_until_idle(max_iterations=1_000_000)
            if wave in kill_waves:
                # Victims must own units: a unit-less shard never
                # applies, so a scripted SITE_APPLY crash aimed at it
                # would silently never fire (under-fired storm).
                owners = [s.index for s in scp.shards
                          if scp.plan.units_of(s.index)]
                victim = owners[rng.randrange(len(owners))]
                if rng.random() < 0.5:
                    # Clean kill between cycles.
                    scp.kill_shard(victim)
                else:
                    # Mid-cycle crash via the victim's OWN scope: the
                    # other shards' cycles never consume this schedule.
                    mid_cycle_kills += 1
                    faultinject.install(
                        FaultInjector({faultinject.SITE_APPLY:
                                       {0: CRASH}}),
                        scope=f"shard-{victim}")
            before = {s.index: s.admitted_total for s in scp.shards}
            dead_before = {s.index for s in scp.shards
                           if s.state != SHARD_ACTIVE}
            scp.cycle()
            clock.advance(1.0)
            scp.renew_leases()
            # Fault isolation: every shard that was ACTIVE when the
            # wave started (and had backlog) must make progress even
            # while a sibling is down.
            dead_now = {s.index for s in scp.shards
                        if s.state != SHARD_ACTIVE}
            if dead_now:
                for s in scp.shards:
                    if (s.index not in dead_now
                            and s.index not in dead_before
                            and scp.plan.units_of(s.index)
                            and s.admitted_total == before[s.index]):
                        survivor_stalls += 1
            # Supervisor: promote the dead on the next wave boundary.
            for s in list(scp.shards):
                if s.state != SHARD_ACTIVE:
                    faultinject.uninstall(scope=s.name)
                    scp.promote_shard(s.index)
                    res.promotions += 1
            res.cycles += 1
        # Drain: no kills, let every backlog clear.
        idle = 0
        while idle < 3 and res.cycles < p["waves"] + 40:
            before_n = _admitted(scp.plane)
            scp.cycle()
            clock.advance(1.0)
            scp.renew_leases()
            res.cycles += 1
            idle = idle + 1 if _admitted(scp.plane) == before_n else 0
    finally:
        for s in scp.shards:
            faultinject.uninstall(scope=s.name)
    res.submitted = n
    res.admitted = _admitted(scp.plane)
    res.admissions = res.admitted
    res.duration_s = clock.now() - 1000.0
    res.counters["kills"] = len(kill_waves)
    res.counters["mid_cycle_kills"] = mid_cycle_kills
    res.counters["promotions"] = res.promotions
    res.counters["per_shard_admitted"] = [
        s.admitted_total for s in scp.shards]
    res.counters["epochs"] = [s.epoch for s in scp.shards]
    res.counters["survivor_stalls"] = survivor_stalls

    if res.admitted < res.submitted:
        res.violations.append(
            f"lost/stranded: {res.submitted - res.admitted} of "
            f"{res.submitted} never admitted after the drain")
    ok, msg = _usage_consistent(scp.plane)
    if not ok:
        res.violations.append(f"double-admission detector: {msg}")
    for s in scp.shards:
        if s.epoch != 1 + s.promotions:
            res.violations.append(
                f"{s.name}: lease epoch {s.epoch} != "
                f"1 + {s.promotions} promotions (fencing hole)")
    if survivor_stalls:
        res.violations.append(
            f"survivors stalled {survivor_stalls} time(s) during an "
            "outage (fault isolation broken)")
    if res.promotions < len(kill_waves):
        res.violations.append(
            f"storm under-fired: {res.promotions} promotions < "
            f"{len(kill_waves)} scheduled kills")
    scp.shutdown()
    if scp.plane.cache.live_handouts:
        res.violations.append(
            f"{scp.plane.cache.live_handouts} snapshot handout(s) "
            "leaked after shutdown")
    return res


def run_shard_rebalance(seed: int = 0,
                        scale: str = "full") -> ScenarioResult:
    p = {"smoke": dict(waves=8, cqs=4, shards=2, moves=1),
         "full": dict(waves=24, cqs=8, shards=4, moves=3),
         }[scale]
    scp, clock = _build_plane(p["shards"], p["cqs"],
                              quota=1000 * (p["waves"] + 1))
    rng = random.Random(seed ^ 0x2EB)
    move_waves = sorted(rng.sample(range(2, p["waves"] - 2), p["moves"]))
    res = ScenarioResult(name="shard_rebalance", seed=seed, scale=scale,
                         backend=_backend_info())
    res.slo = SLOSpec(min_admitted=p["waves"] * p["cqs"])
    n = 0
    moves = []         # {unit, from, to, wave, ttfa_cycles}
    pending_ttfa = []  # moves waiting for the new owner's first admit
    old_owner_leaks = 0
    for wave in range(p["waves"]):
        for i in range(p["cqs"]):
            scp.plane.store.create(_workload(wave, i, n))
            n += 1
        scp.plane.run_until_idle(max_iterations=1_000_000)
        if wave in move_waves:
            # Move a seeded unit to the least-loaded OTHER shard.
            units = list(scp.plan.shard_of_unit)
            unit = units[rng.randrange(len(units))]
            frm = scp.plan.shard_of_unit[unit]
            to = min((s.index for s in scp.shards if s.index != frm),
                     key=lambda j: scp.plan.loads[j]
                     if j < len(scp.plan.loads) else 0)
            rep = scp.rebalance(unit, to)
            if rep["moved"]:
                mv = {"unit": unit, "from": frm, "to": to,
                      "wave": wave, "ttfa_cycles": None,
                      "old_admitted_at_move":
                          scp.shards[frm].admitted_total,
                      "new_admitted_at_move":
                          scp.shards[to].admitted_total,
                      "cycles_waited": 0}
                moves.append(mv)
                pending_ttfa.append(mv)
        scp.cycle()
        clock.advance(1.0)
        scp.renew_leases()
        res.cycles += 1
        for mv in list(pending_ttfa):
            mv["cycles_waited"] += 1
            if (scp.shards[mv["to"]].admitted_total
                    > mv["new_admitted_at_move"]):
                mv["ttfa_cycles"] = mv["cycles_waited"]
                pending_ttfa.remove(mv)
    # Drain.
    idle = 0
    while idle < 3 and res.cycles < p["waves"] + 40:
        before_n = _admitted(scp.plane)
        scp.cycle()
        clock.advance(1.0)
        scp.renew_leases()
        res.cycles += 1
        idle = idle + 1 if _admitted(scp.plane) == before_n else 0
        for mv in list(pending_ttfa):
            mv["cycles_waited"] += 1
            if (scp.shards[mv["to"]].admitted_total
                    > mv["new_admitted_at_move"]):
                mv["ttfa_cycles"] = mv["cycles_waited"]
                pending_ttfa.remove(mv)
    # The old owner must admit NOTHING from a moved unit after its
    # fence: check by CQ attribution in the store (admission records
    # carry the CQ; the plan maps CQ -> owner at drain time).
    for wl in scp.plane.store.list("Workload", copy_objects=False):
        if not wlpkg.has_quota_reservation(wl):
            continue
    # (Store admission records carry no shard identity — ownership is
    # proven by the counter deltas below instead: after a move the old
    # owner's counter may only grow by its REMAINING units' traffic.)
    for mv in moves:
        frm_cqs_after = set(scp.plan.cqs_of(mv["from"]))
        # Units the old owner kept: its counter growth is legitimate
        # only if it still owns at least one unit; an owner stripped of
        # every unit must not admit at all after the fence.
        if not frm_cqs_after:
            grew = (scp.shards[mv["from"]].admitted_total
                    - mv["old_admitted_at_move"])
            if grew:
                old_owner_leaks += grew

    res.submitted = n
    res.admitted = _admitted(scp.plane)
    res.admissions = res.admitted
    res.duration_s = clock.now() - 1000.0
    res.counters["moves"] = [
        {k: mv[k] for k in ("unit", "from", "to", "wave",
                            "ttfa_cycles")} for mv in moves]
    res.counters["rebalances"] = scp.rebalances
    res.counters["per_shard_admitted"] = [
        s.admitted_total for s in scp.shards]
    res.counters["plan_fingerprint"] = scp.plan.fingerprint

    if not moves:
        res.violations.append("no rebalance ever moved a unit "
                              "(scenario vacuous)")
    for mv in moves:
        if mv["ttfa_cycles"] is None:
            res.violations.append(
                f"rebalance {mv['unit']} -> shard {mv['to']}: new "
                f"owner never admitted (unbounded TTFA)")
        elif mv["ttfa_cycles"] > MAX_TTFA_CYCLES:
            res.violations.append(
                f"rebalance {mv['unit']} -> shard {mv['to']}: TTFA "
                f"{mv['ttfa_cycles']} cycles > {MAX_TTFA_CYCLES}")
    if old_owner_leaks:
        res.violations.append(
            f"fenced old owner admitted {old_owner_leaks} workload(s) "
            "after losing its last unit")
    if res.admitted < res.submitted:
        res.violations.append(
            f"lost/stranded: {res.submitted - res.admitted} of "
            f"{res.submitted} never admitted after the drain")
    ok, msg = _usage_consistent(scp.plane)
    if not ok:
        res.violations.append(f"double-admission detector: {msg}")
    scp.shutdown()
    if scp.plane.cache.live_handouts:
        res.violations.append(
            f"{scp.plane.cache.live_handouts} snapshot handout(s) "
            "leaked after shutdown")
    return res
