"""Seeded, deterministic traffic traces for the scenario suite.

The perf generator (perf/generator.py) emits the reference harness's
uniform-interval arrival schedule — fine for throughput measurement,
nothing like production traffic. Real million-user load is diurnal
(sinusoidal base rate), bursty (harmonic spikes riding the wave) and
adversarial (one tenant flooding while others trickle). This module
produces those shapes as plain arrival lists from a seeded PRNG, so a
scenario run is reproducible bit-for-bit from (seed, parameters) and a
failure can be replayed by seed alone.

Arrival times come from an inhomogeneous Poisson process sampled by
thinning (Lewis & Shedler): draw candidate points at the peak rate,
keep each with probability rate(t)/rate_max. Priority classes are
sampled per arrival from a weighted distribution, mirroring the
small/medium/large class mix of the perf harness.

All times are virtual seconds on the scenario's FakeClock.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Callable, Optional

# Priority-class mix: (class name, priority, resource units, runtime s,
# sample weight). Mirrors the reference harness's small/medium/large
# shape: many cheap low-priority workloads, few expensive high-priority
# ones (default_generator_config.yaml:1-28).
PRIORITY_CLASSES = (
    ("batch", 0, 1, 30.0, 0.6),
    ("standard", 50, 2, 60.0, 0.3),
    ("prod", 100, 4, 90.0, 0.1),
)


@dataclass
class TraceArrival:
    """One workload arrival. ``tenant`` indexes the scenario's
    LocalQueues; ``kind`` selects the object the driver creates
    ("workload" = a bare Workload; mixed-job scenarios map framework
    names like "job"/"jobset"/"pytorch"/"ray" to their wrappers)."""
    at_s: float
    tenant: int
    class_name: str
    priority: int
    request: int        # abstract resource units (the harness's "cpu")
    runtime_s: float
    kind: str = "workload"


def _sample_class(rng: random.Random) -> tuple:
    r = rng.random()
    acc = 0.0
    for cls in PRIORITY_CLASSES:
        acc += cls[4]
        if r <= acc:
            return cls
    return PRIORITY_CLASSES[-1]


def poisson_times(rng: random.Random, rate_fn: Callable[[float], float],
                  rate_max: float, duration_s: float) -> list:
    """Inhomogeneous Poisson arrival times on [0, duration_s) by
    thinning: candidates at ``rate_max``, accepted with probability
    rate_fn(t)/rate_max. ``rate_max`` must dominate rate_fn."""
    if rate_max <= 0:
        return []
    out: list = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_max)
        if t >= duration_s:
            return out
        if rng.random() * rate_max <= rate_fn(t):
            out.append(t)


def diurnal_rate(base: float, amplitude: float, period_s: float,
                 bursts: Optional[list] = None) -> tuple:
    """(rate_fn, rate_max) for a sinusoidal arrival rate with burst
    harmonics: rate(t) = base * (1 + amplitude * sin(2πt/period)) plus,
    for each (center_s, width_s, extra) burst, ``extra`` arrivals/s
    while |t - center| <= width — the traffic spikes riding the diurnal
    wave."""
    bursts = bursts or []

    def rate(t: float) -> float:
        r = base * (1.0 + amplitude * math.sin(2.0 * math.pi * t / period_s))
        for center, width, extra in bursts:
            if abs(t - center) <= width:
                r += extra
        return max(0.0, r)

    rate_max = base * (1.0 + abs(amplitude)) \
        + sum(extra for _, _, extra in bursts)
    return rate, rate_max


def diurnal_trace(seed: int, duration_s: float = 600.0, tenants: int = 6,
                  base_rate: float = 0.4, amplitude: float = 0.8,
                  period_s: Optional[float] = None,
                  bursts: Optional[list] = None) -> list:
    """Scenario (a) traffic: a sinusoidal wave over ``duration_s`` with
    two default burst harmonics (one near each rate peak), arrivals
    spread over ``tenants`` round-robin-with-jitter, classes sampled
    from PRIORITY_CLASSES."""
    rng = random.Random(seed)
    period = period_s if period_s is not None else duration_s / 2.0
    if bursts is None:
        # one spike per wave period, riding the crest
        bursts = [(period * (k + 0.25), period * 0.05, base_rate * 3.0)
                  for k in range(max(1, int(duration_s / period)))]
    rate_fn, rate_max = diurnal_rate(base_rate, amplitude, period, bursts)
    out = []
    for t in poisson_times(rng, rate_fn, rate_max, duration_s):
        name, prio, req, runtime, _w = _sample_class(rng)
        out.append(TraceArrival(
            at_s=t, tenant=rng.randrange(tenants), class_name=name,
            priority=prio, request=req, runtime_s=runtime))
    return out


def steady_trace(seed: int, duration_s: float, tenants: int,
                 interval_s: float, jitter: float = 0.25,
                 kinds: Optional[list] = None) -> list:
    """A per-tenant steady trickle: one arrival every ``interval_s``
    per tenant, with ±jitter de-phasing so tenants don't arrive in
    lockstep. ``kinds`` (optional) cycles arrival kinds per tenant —
    the mixed-job scenario feeds framework names here."""
    rng = random.Random(seed)
    out = []
    for tenant in range(tenants):
        t = rng.uniform(0, interval_s)
        i = 0
        while t < duration_s:
            name, prio, req, runtime, _w = _sample_class(rng)
            kind = kinds[(tenant + i) % len(kinds)] if kinds else "workload"
            out.append(TraceArrival(
                at_s=t, tenant=tenant, class_name=name, priority=prio,
                request=req, runtime_s=runtime, kind=kind))
            t += interval_s * (1.0 + jitter * (2.0 * rng.random() - 1.0))
            i += 1
    out.sort(key=lambda a: a.at_s)
    return out


def storm_trace(seed: int, duration_s: float, tenants: int,
                storm_tenant: int = 0, storm_at_s: float = 60.0,
                storm_count: int = 120, storm_width_s: float = 10.0,
                trickle_interval_s: float = 20.0) -> list:
    """Scenario (b) traffic: every tenant trickles steadily, and at
    ``storm_at_s`` the storm tenant floods ``storm_count`` arrivals
    inside ``storm_width_s`` — the adversarial neighbor whose backlog
    must not starve anyone else's queue."""
    rng = random.Random(seed)
    out = steady_trace(seed + 1, duration_s, tenants, trickle_interval_s)
    for _ in range(storm_count):
        name, prio, req, runtime, _w = _sample_class(rng)
        out.append(TraceArrival(
            at_s=storm_at_s + rng.uniform(0, storm_width_s),
            tenant=storm_tenant, class_name=name, priority=prio,
            request=req, runtime_s=runtime))
    out.sort(key=lambda a: a.at_s)
    return out


def burst_trace(seed: int, tenants: int, per_tenant: int,
                at_s: float = 0.0, width_s: float = 5.0,
                class_name: str = "standard", priority: int = 50,
                request: int = 1, runtime_s: float = 120.0) -> list:
    """A synchronized wave: ``per_tenant`` same-class arrivals per
    tenant inside ``width_s`` — the shape that makes every admitted
    workload hit a PodsReady timeout (or a lost worker cluster) at
    nearly the same instant, i.e. the retry-storm seed."""
    rng = random.Random(seed)
    out = [TraceArrival(
        at_s=at_s + rng.uniform(0, width_s), tenant=tenant,
        class_name=class_name, priority=priority, request=request,
        runtime_s=runtime_s)
        for tenant in range(tenants) for _ in range(per_tenant)]
    out.sort(key=lambda a: a.at_s)
    return out
