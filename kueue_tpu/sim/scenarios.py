"""Trace-driven production-realism scenarios with SLO gates (ISSUE 8).

Each scenario drives the FULL control plane (KueueManager: sim store,
webhooks, controllers, scheduler) through a seeded traffic trace
(sim/traces.py) on the virtual FakeClock, playing the job-framework's
part for plain Workloads (completing evictions, finishing runs, flipping
PodsReady) so the admission/eviction/requeue loop closes end-to-end.
Results are gated by perf.checker.SLOSpec bounds — per-priority-class
p99 time-to-admission, degradation-ladder recovery, requeue
amplification, and the zero-starvation invariant — all in VIRTUAL
seconds, so the gates are deterministic for a (seed, scale) pair and
backend-agnostic by construction (an SLOSpec that bounds wall behavior
instead declares its backend and cross-backend comparison is refused,
per perf.checker.refuse_cross_backend).

The catalog (sim/SCENARIOS.md documents each in detail):

- ``diurnal``       (a) sinusoidal arrival wave with burst harmonics
- ``tenant_storm``  (b) one LocalQueue floods while others trickle
- ``flavor_churn``  (c) ClusterQueue quota edits mid-traffic (per-CQ
                        epoch / partial-rebuild path)
- ``requeue_flood`` (d) waitForPodsReady timeout storm -> mass eviction
                        -> jittered requeue backoff (SURVEY.md §5)
- ``cluster_loss``  (e) MultiKueue worker loss mid-dispatch, re-place,
                        rejoin, orphan GC (SURVEY.md §5)
- ``mixed_jobs``    (f) jobset/kubeflow/ray/batch-job traffic under
                        load, parity with the plain-workload path
- ``restart_storm`` (g) the control plane crashes at seeded mid-cycle
                        points and restores from the durable store
                        (RESILIENCE.md §6); gated on zero starvation +
                        recovery-to-first-admission
- ``visibility_storm`` (h) reader threads hammer the snapshot-backed
                        query plane concurrently with admission traffic
                        and quota churn; gated on read consistency,
                        bounded response-token staleness, and zero
                        handout leaks (obs/queryplane.py / ISSUE 12)
- ``cluster_rebalance`` (i) MultiKueue cluster loss/rejoin MID-storm on
                        the batched-column placement path (ISSUE 13);
                        gated on zero double-dispatch, bounded
                        re-placement latency
                        (SLOSpec.max_replacement_latency_s) and the
                        planned single-mirror execution engaging
- ``failover``      (j) the leader is killed mid-storm and the HOT
                        STANDBY promotes (resilience/replica.py +
                        RESILIENCE.md §7) — no cold restore; gated on
                        promotion-to-first-admission
                        (SLOSpec.max_promotion_to_first_admission_s,
                        well under the restart_storm cold budget),
                        zero double admission (store-vs-cache usage
                        cross-check) and zero starvation

Run one via ``run_scenario(name, seed=..., scale="smoke"|"full")`` or
end-to-end with artifacts via ``tools/scenario_run.py``.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from kueue_tpu import config as cfgpkg
from kueue_tpu.api import batchv1
from kueue_tpu.api import jobset as jobsetapi
from kueue_tpu.api import kubeflow as kf
from kueue_tpu.api import kueue as api
from kueue_tpu.api import ray as rayapi
from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
from kueue_tpu.api.meta import (Condition, FakeClock, LabelSelector,
                                ObjectMeta, find_condition, set_condition)
from kueue_tpu.core import workload as wlpkg
from kueue_tpu.perf.checker import SLOSpec, check_slo
from kueue_tpu.sim import AlreadyExists
from kueue_tpu.sim.traces import (TraceArrival, burst_trace, diurnal_trace,
                                  steady_trace, storm_trace)

CLASS_LABEL = "scenario.kueue-tpu/class"
TENANT_LABEL = "scenario.kueue-tpu/tenant"

UNIT = 1000  # one abstract resource unit = 1000 milli-cpu

# Recent-cycle (tag, route, regime) ring capacity: large enough that
# every catalog scenario's route-coverage gate sees its whole run (the
# longest full-scale scenario seals a few hundred cycles), small enough
# that a multi-day composed soak can't grow the harness without bound
# (sim/soak.py; lifetime counts live in the bounded-cardinality
# ``route_mix`` aggregate instead).
ROUTE_RING_CAPACITY = 4096


# ----------------------------------------------------------------------
# result
# ----------------------------------------------------------------------

@dataclass
class ScenarioResult:
    """What one scenario run observed, plus its SLO verdict. All times
    are virtual seconds; ``backend`` stamps the env the run executed on
    (informational for virtual-time gates — see module docstring)."""
    name: str
    seed: int
    scale: str
    backend: dict = field(default_factory=dict)
    cycles: int = 0
    duration_s: float = 0.0
    submitted: int = 0
    admitted: int = 0        # distinct workloads ever admitted
    admissions: int = 0      # admission transitions incl. re-admissions
    evictions: int = 0       # lifetime EvictedDueTo* event count
    starved: list = field(default_factory=list)
    class_p99_tta_s: dict = field(default_factory=dict)
    # 0 = ladder never engaged; N = cycles from storm end back to the
    # normal rung; None = engaged but never recovered (an SLO violation
    # when the spec bounds recovery).
    ladder_recovery_cycles: Optional[int] = 0
    # Crash-restart scenario (g): how often the control plane was
    # killed + restored, and the virtual seconds from each restore back
    # to the next admission grant (the recovery-to-first-admission SLO).
    restarts: int = 0
    recovery_to_first_admission_s: list = field(default_factory=list)
    # Hot-standby failover scenario (j / RESILIENCE.md §7): standby
    # promotions and the virtual seconds from each promotion back to
    # the next admission grant (the promotion-to-first-admission SLO —
    # the warm analogue of the restart fields above).
    promotions: int = 0
    promotion_to_first_admission_s: list = field(default_factory=list)
    # Query-plane read storm (scenario h / ISSUE 12): reads served and
    # the worst structural-generation lag any stamped response showed
    # vs the live cache at read time (None = no samples recorded).
    reads: int = 0
    read_staleness_generations: Optional[int] = None
    # Cluster-rebalance scenario (i / ISSUE 13): virtual seconds from a
    # worker-cluster loss to the LAST affected workload re-reserving on
    # a surviving cluster through the batched-column path (None = no
    # affected workloads, or they never re-placed).
    replacement_latency_s: Optional[float] = None
    requeue_amplification: float = 0.0
    counters: dict = field(default_factory=dict)
    violations: list = field(default_factory=list)
    slo: Optional[SLOSpec] = None

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "scenario": self.name, "seed": self.seed, "scale": self.scale,
            "backend": dict(self.backend),
            "cycles": self.cycles, "duration_s": self.duration_s,
            "submitted": self.submitted, "admitted": self.admitted,
            "admissions": self.admissions, "evictions": self.evictions,
            "starved": sorted(self.starved),
            "class_p99_tta_s": {k: round(v, 3)
                                for k, v in self.class_p99_tta_s.items()},
            "ladder_recovery_cycles": self.ladder_recovery_cycles,
            "restarts": self.restarts,
            "recovery_to_first_admission_s": [
                round(v, 3) for v in self.recovery_to_first_admission_s],
            "promotions": self.promotions,
            "promotion_to_first_admission_s": [
                round(v, 3) for v in self.promotion_to_first_admission_s],
            "reads": self.reads,
            "read_staleness_generations": self.read_staleness_generations,
            "replacement_latency_s": (
                round(self.replacement_latency_s, 3)
                if self.replacement_latency_s is not None else None),
            "requeue_amplification": round(self.requeue_amplification, 3),
            "counters": dict(self.counters),
            "ok": self.ok, "violations": list(self.violations),
        }


def _backend_info() -> dict:
    """Best-effort backend stamp (matches bench.py's BACKEND shape);
    scenarios never dispatch to a device, so this is provenance only."""
    try:
        import jax
        return {"backend": jax.default_backend(), "cpu_fallback": False}
    except Exception:
        return {"backend": "none", "cpu_fallback": False}


# ----------------------------------------------------------------------
# harness
# ----------------------------------------------------------------------

class ScenarioHarness:
    """Drives one KueueManager (plus optional MultiKueue workers)
    through a TraceArrival list on the shared FakeClock.

    The harness plays the job-framework role for plain Workloads: it
    completes evictions (unset reservation + Requeued=False, the way
    jobframework stopJob does), finishes runs after their trace
    runtime, and flips PodsReady per the scenario's policy. Workloads
    created through a job integration (scenario f) are left to the real
    reconcilers.
    """

    def __init__(self, name: str, seed: int, *, tenants: int,
                 quota_units: int, cohorts: int = 1,
                 cfg: Optional[cfgpkg.Configuration] = None,
                 cycle_s: float = 5.0,
                 reclaim_within_cohort: str = api.PREEMPTION_ANY,
                 remote_clusters: Optional[list] = None,
                 mk_check: bool = False, solver=None,
                 durable: bool = False, standby: bool = False,
                 standby_poll_every: int = 1):
        from kueue_tpu.manager import KueueManager
        self.name = name
        self.seed = seed
        self.tenants = tenants
        self.cycle_s = cycle_s
        self._cfg = cfg
        self.clock = FakeClock(1000.0)
        self.workers: dict = {}
        for cname in remote_clusters or []:
            # Workers carry the SAME tenant layout: a mirror keeps the
            # origin's LocalQueue name, so it only queues on a worker
            # that has that queue (reference: identical object names
            # across the fleet, SURVEY.md §2.7).
            worker = KueueManager(clock=self.clock)
            self._create_capacity(worker, tenants, quota_units, cohorts,
                                  reclaim_within_cohort)
            self.workers[cname] = worker
        self.mgr = KueueManager(
            cfg=cfg, clock=self.clock, solver=solver,
            remote_clusters=self.workers or None)
        # Crash-restart support (scenario g / RESILIENCE.md §6): with
        # durable=True every store mutation journals to an in-memory
        # checkpoint/WAL log — the "disk" that survives a simulated
        # process death — and step() restores a fresh manager from it
        # when an InjectedCrash kills the control plane mid-cycle.
        self.durable = None
        if durable:
            from kueue_tpu.sim.durable import DurableLog
            self.durable = DurableLog(checkpoint_every=4096)
            self.mgr.store.attach_durable(self.durable)
            self.mgr.durable = self.durable
        self._solver = solver
        self.restarts = 0
        self.recovery_ttas: list = []      # virtual s, restore -> admit
        self._recovery_pending: Optional[float] = None
        self._adm_at_restore = 0
        # Hot-standby failover (scenario j / RESILIENCE.md §7): with
        # standby=True a StandbyReplica tails the durable log (polled
        # every ``standby_poll_every`` cycles — the lag-state knob the
        # promotion-timing sweeps vary) and a crash PROMOTES it instead
        # of cold-restoring; the initial leader is fenced via lead().
        if standby and not durable:
            raise ValueError("standby=True requires durable=True")
        if standby and solver is not None:
            # The cold-restore path reuses the harness solver AFTER the
            # leader dies; a standby would have to detach() it out from
            # under the LIVE leader at construction. Loud, not silent:
            # give the replica its own solver via StandbyReplica
            # directly if a scenario needs the device path warm.
            raise ValueError(
                "standby=True cannot share the harness solver with "
                "the live leader; construct the StandbyReplica with "
                "its own solver instead")
        self.standby = None
        self.standby_poll_every = max(1, standby_poll_every)
        self.promotions = 0
        self.promotion_ttas: list = []     # virtual s, promote -> admit
        self._promotion_pending: Optional[float] = None
        self._adm_at_promote = 0
        self._want_standby = standby
        # Lifetime event counts observed from managers that have since
        # crashed: the EventRecorder dies with its process, but the
        # harness (the outside observer) saw the events live — SLO
        # gates on evictions/requeues must count across restarts.
        self._evictions_carry = 0
        # Per-cycle (tag, route, regime) stream read off the flight
        # recorder as cycles seal — the ring is bounded, so sampling at
        # step() time survives rotation on long scenarios. Feeds the
        # route-coverage gates (e.g. tenant_storm's "preemption-heavy
        # phases route to device" check when a solver is attached).
        # Bounded on BOTH axes so a multi-day composed soak can't grow
        # the harness: the ring holds the most recent cycles, the
        # ``route_mix`` aggregate holds lifetime counts at (tag, route,
        # regime) cardinality, and dedup against re-reading the same
        # sealed trace is a scalar high-water mark, not a seen-id set.
        self.cycle_routes: deque = deque(maxlen=ROUTE_RING_CAPACITY)
        self.route_mix: dict = {}       # (tag, route, regime) -> count
        self._last_cycle_seen: Optional[int] = None
        check_names = []
        if mk_check:
            from kueue_tpu.api import autoscaling as asapi
            from kueue_tpu.controller.admissionchecks.multikueue import \
                CONTROLLER_NAME as MK_CONTROLLER
            for cname in self.workers:
                self.mgr.store.create(asapi.MultiKueueCluster(
                    metadata=ObjectMeta(name=cname)))
            self.mgr.store.create(asapi.MultiKueueConfig(
                metadata=ObjectMeta(name="mk-config"),
                spec=asapi.MultiKueueConfigSpec(clusters=list(self.workers))))
            ac = api.AdmissionCheck(metadata=ObjectMeta(name="mk-check"))
            ac.spec.controller_name = MK_CONTROLLER
            ac.spec.parameters = api.AdmissionCheckParametersReference(
                kind="MultiKueueConfig", name="mk-config")
            self.mgr.store.create(ac)
            check_names = ["mk-check"]
        self._create_capacity(self.mgr, tenants, quota_units, cohorts,
                              reclaim_within_cohort, check_names)
        self.mgr.run_until_idle()
        if self._want_standby:
            # Capacity is journaled by now, so the follower bootstraps
            # warm; the leader takes the fenced lease (epoch 1) — a
            # promotion bumps it and fences whatever is left of the
            # old process.
            from kueue_tpu.resilience.replica import lead
            lead(self.mgr, self.durable, identity="leader-0")
            self.standby = self._make_standby()

        self._seq = 0
        self.cycles = 0
        self.t0 = self.clock.now()
        self.arrival_info: dict = {}   # object name -> TraceArrival
        self.submitted = 0
        self.first_admit: dict = {}    # workload name -> tta (virtual s)
        self.kind_of_wl: dict = {}     # workload name -> owner kind
        self.class_of_wl: dict = {}    # workload name -> priority class
        self.tenant_of_wl: dict = {}   # workload name -> tenant index
        self.admissions = 0
        self._reserved: set = set()
        self._finish_at: dict = {}     # workload name -> virtual due time
        self._ready_at: dict = {}      # workload name -> virtual due time
        # policy(workload_name) -> delay after admission until
        # PodsReady=True, or None = pods never become ready.
        self.pods_ready_policy: Optional[Callable[[str], Optional[float]]] = None
        self.requeue_ats: list = []    # observed requeue_state.requeue_at
        # ladder bookkeeping (cycles from storm end to the normal rung)
        self._storm_end_cycle: Optional[int] = None
        self._ladder_engaged = False
        self._ladder_recovery: Optional[int] = None

    # -- cluster construction ------------------------------------------

    @staticmethod
    def _create_capacity(mgr, tenants: int, quota_units: int, cohorts: int,
                         reclaim: str, check_names: list = ()) -> None:
        rf = api.ResourceFlavor(metadata=ObjectMeta(name="default",
                                                    uid="rf-default"))
        mgr.store.create(rf)
        for t in range(tenants):
            cq = api.ClusterQueue(metadata=ObjectMeta(
                name=f"cq-t{t}", uid=f"cq-t{t}"))
            cq.spec.namespace_selector = LabelSelector()
            cq.spec.cohort = f"cohort-{t % cohorts}"
            cq.spec.resource_groups.append(api.ResourceGroup(
                covered_resources=["cpu"],
                flavors=[api.FlavorQuotas(name="default", resources=[
                    api.ResourceQuota(name="cpu",
                                      nominal_quota=quota_units * UNIT)])]))
            cq.spec.preemption = api.ClusterQueuePreemption(
                reclaim_within_cohort=reclaim)
            if check_names:
                cq.spec.admission_checks = list(check_names)
            mgr.store.create(cq)
            lq = api.LocalQueue(metadata=ObjectMeta(
                name=f"lq-t{t}", namespace="default", uid=f"lq-t{t}"))
            lq.spec.cluster_queue = f"cq-t{t}"
            mgr.store.create(lq)
        mgr.run_until_idle()

    # -- traffic -------------------------------------------------------

    def set_phase(self, tag: str) -> None:
        """Stamp subsequent cycle traces with a scenario phase tag (the
        flight-recorder windowing handle for SLO evaluation)."""
        self.mgr.flight_recorder.set_tag(tag)

    def set_objectives(self, slo) -> None:
        """Price the journey ledger's live SLI stream against this
        scenario's SLOSpec (perf.checker.journey_objectives): sealed
        journeys exceeding their class p99 bound burn the error budget
        and are retained as violation exemplars. The spec is kept on
        the harness so _restore_after_crash can re-price the REBUILT
        manager's ledger — a restored or promoted manager starts with
        an unpriced ledger, and without re-application the burn-rate
        SLI stream silently goes dark after the first crash."""
        self._slo_objectives = slo
        led = getattr(self.mgr, "journey_ledger", None)
        if led is not None:
            from kueue_tpu.perf.checker import journey_objectives
            led.set_objectives(journey_objectives(slo))

    def mark_storm_end(self) -> None:
        self._storm_end_cycle = self.cycles
        self.set_phase("recovery")

    def submit(self, arr: TraceArrival) -> None:
        """Deliver one arrival. On a durable harness (scenario g) this
        survives the apiserver dying mid-create: the ``store_write``
        crash window sits AFTER the WAL append, so the object can be
        durable even though the create never returned — the client
        restores the plane and retries UNDER THE SAME NAME, treating
        AlreadyExists as success (idempotent re-reconcile, like any
        real job controller; a fresh-name retry would mint a duplicate
        workload for one logical arrival). Bookkeeping runs only after
        the object exists, so a lost create never leaves a dangling
        arrival_info entry or an inflated submitted count."""
        from kueue_tpu.resilience.faultinject import InjectedCrash
        self._seq += 1
        name = f"{arr.kind}{self._seq}-t{arr.tenant}"
        builder = _BUILDERS[arr.kind]
        while True:
            try:
                self.mgr.store.create(builder(name, f"lq-t{arr.tenant}",
                                              arr, self.clock.now()))
                break
            except AlreadyExists:
                if self.durable is None:
                    raise  # a real naming bug, not a crash retry
                break  # the pre-crash create reached the WAL
            except InjectedCrash:
                if self.durable is None:
                    raise
                self._restore_after_crash()
        self.arrival_info[name] = arr
        self.submitted += 1

    # -- the cycle loop ------------------------------------------------

    def run(self, arrivals: list, duration_s: float,
            hooks: Optional[list] = None) -> None:
        """Feed ``arrivals`` (sorted TraceArrivals, at_s relative to run
        start) over ``duration_s`` virtual seconds of scheduler cycles.
        ``hooks`` is a list of (at_s, fn) fired once when the virtual
        offset is reached — quota edits, cluster loss, phase flips."""
        pending = sorted(arrivals, key=lambda a: a.at_s)
        hooks = sorted(hooks or [], key=lambda h: h[0])
        start = self.clock.now()
        i = h = 0
        while self.clock.now() - start < duration_s:
            offset = self.clock.now() - start
            while h < len(hooks) and hooks[h][0] <= offset:
                hooks[h][1]()
                h += 1
            while i < len(pending) and pending[i].at_s <= offset:
                self.submit(pending[i])
                i += 1
            self.step()
        while i < len(pending):   # stragglers past the window
            self.submit(pending[i])
            i += 1
        while h < len(hooks):
            hooks[h][1]()
            h += 1

    def drain(self, max_cycles: int = 120) -> None:
        """Keep cycling with no new arrivals until every submitted
        workload is finished or holding a reservation (requeue backoffs
        have flushed), or the cycle cap is hit."""
        for _ in range(max_cycles):
            if self._settled():
                return
            self.step()

    def _settled(self) -> bool:
        for wl in self.mgr.store.list("Workload", copy_objects=False):
            if wlpkg.is_finished(wl) or not wlpkg.is_active(wl):
                continue
            if not wlpkg.has_quota_reservation(wl):
                return False
            if wlpkg.is_evicted(wl):
                return False   # eviction still completing
        return True

    def step(self) -> None:
        from kueue_tpu.resilience.faultinject import InjectedCrash
        # Progress markers so the crash handler completes EXACTLY what
        # the dying step didn't: a kill landing in the timer drain —
        # after the body already counted the cycle and advanced the
        # clock — must not count or advance a second time (it would
        # inflate every virtual-time SLO sample for that kill).
        self._step_counted = False
        self._step_advanced = False
        try:
            if (self.standby is not None
                    and self.cycles % self.standby_poll_every == 0):
                # The follower's heartbeat: tail replay at (a fraction
                # of) cycle cadence. Runs BEFORE the leader's cycle so
                # the lag at a kill point reflects the poll interval,
                # not the step's own appends.
                self.standby.poll()
            self._step_body()
        except InjectedCrash:
            # Simulated process death mid-step (scenario g): store
            # writes happen in reconciles, the admission cycle AND the
            # timer drain, so the crash can surface anywhere in the
            # body. Only a durable harness can survive it; the lost
            # step's in-memory work is gone by design — the store
            # replay on restore is the recovery contract under test.
            if self.durable is None:
                raise
            self._restore_after_crash()
            if not self._step_counted:
                self.cycles += 1
            if not self._step_advanced:
                self.clock.advance(self.cycle_s)

    def _step_body(self) -> None:
        self.mgr.run_until_idle()
        self.mgr.scheduler.schedule(timeout=0)
        self.mgr.run_until_idle()
        for worker in self.workers.values():
            worker.scheduler.schedule(timeout=0)
            worker.run_until_idle()
        if self.workers:
            self.mgr.run_until_idle()
        self._observe()
        tr = self.mgr.flight_recorder.last()
        if tr is not None and (self._last_cycle_seen is None
                               or tr.cycle_id > self._last_cycle_seen):
            self._last_cycle_seen = tr.cycle_id
            key = (tr.tag, tr.route, tr.regime)
            self.cycle_routes.append(key)
            self.route_mix[key] = self.route_mix.get(key, 0) + 1
        if self._recovery_pending is not None \
                and self.admissions > self._adm_at_restore:
            # First admission grant since the restore: the
            # recovery-to-first-admission SLO sample (virtual seconds).
            self.recovery_ttas.append(
                self.clock.now() - self._recovery_pending)
            self._recovery_pending = None
        if self._promotion_pending is not None \
                and self.admissions > self._adm_at_promote:
            # First admission grant since a standby promotion: the
            # promotion-to-first-admission SLO sample (virtual s).
            self.promotion_ttas.append(
                self.clock.now() - self._promotion_pending)
            self._promotion_pending = None
        self.cycles += 1
        self._step_counted = True
        self._track_ladder()
        self.mgr.advance(self.cycle_s)
        self._step_advanced = True
        for worker in self.workers.values():
            worker.runtime.advance(0.0)
        if self.workers:
            self.mgr.run_until_idle()

    def _restore_after_crash(self) -> None:
        """The simulated process died (InjectedCrash propagated out of
        a cycle): throw the manager away and rebuild it from the
        durable log on the shared virtual clock. The harness's
        observation maps (arrivals, first-admit times, reserved set)
        model the OUTSIDE world — jobs and operators — so they survive
        the restart; everything inside the dead manager must come back
        from the store alone (resilience/recovery.py)."""
        from kueue_tpu.resilience import faultinject, recovery
        faultinject.uninstall()
        # The dead manager's EventRecorder dies with it; bank the
        # lifetime counts the harness already observed so SLO gates
        # stay exact across restarts.
        self._evictions_carry += self.mgr.recorder.count_by_reason_prefix(
            "EvictedDueTo")
        if self.standby is not None:
            # Hot failover (scenario j): no cold restore — the warm
            # follower fences the dead leader's epoch, drains the tail
            # and takes over; a FRESH follower then starts tailing the
            # promoted leader for the next kill.
            self.mgr = self.standby.promote(force=True)
            self.promotions += 1
            self._promotion_pending = self.clock.now()
            self._adm_at_promote = self.admissions
            self.standby = self._make_standby()
        else:
            self.mgr = recovery.restore(
                self.durable, cfg=self._cfg, clock=self.clock,
                solver=self._solver,
                remote_clusters=self.workers or None)
            self.restarts += 1
            self._recovery_pending = self.clock.now()
            self._adm_at_restore = self.admissions
        # Re-price the new manager's journey ledger: objectives live
        # in the ledger, not the durable log, so they do not survive
        # either restore path on their own.
        if getattr(self, "_slo_objectives", None) is not None:
            self.set_objectives(self._slo_objectives)
        self.mgr.flight_recorder.set_tag("recovery")
        # The fresh scheduler's cycle ids restart at 0/1, below the
        # dead manager's high-water mark — reset it or the (tag,
        # route, regime) stream silently ends after the first crash.
        self._last_cycle_seen = None

    def _make_standby(self):
        from kueue_tpu.resilience.replica import StandbyReplica
        # Remote clusters carry through (same external workers the
        # leader mirrors to); the solver deliberately does NOT — see
        # the constructor's standby+solver rejection.
        return StandbyReplica(self.durable, cfg=self._cfg,
                              clock=self.clock,
                              remote_clusters=self.workers or None,
                              identity=f"standby-{self.promotions}")

    # -- observation: the job-framework role for plain workloads -------

    def _observe(self) -> None:
        now = self.clock.now()
        store = self.mgr.store
        for wl in store.list("Workload", copy_objects=False):
            name = wl.metadata.name
            reserved = wlpkg.has_quota_reservation(wl)
            if reserved and name not in self._reserved:
                self._reserved.add(name)
                self.admissions += 1
                arr = self._arrival_for(wl)
                if name not in self.first_admit:
                    qr = find_condition(wl.status.conditions,
                                        api.WORKLOAD_QUOTA_RESERVED)
                    t_adm = qr.last_transition_time if qr else now
                    self.first_admit[name] = max(
                        0.0, t_adm - wl.metadata.creation_timestamp)
                    self.kind_of_wl[name] = self._wl_kind(wl)
                    if arr is not None:
                        self.class_of_wl[name] = arr.class_name
                        self.tenant_of_wl[name] = arr.tenant
                if arr is not None and arr.runtime_s > 0:
                    self._finish_at[name] = now + arr.runtime_s
                if self.pods_ready_policy is not None:
                    delay = self.pods_ready_policy(name)
                    if delay is not None:
                        self._ready_at[name] = now + delay
                    else:
                        self._ready_at.pop(name, None)
            elif not reserved and name in self._reserved:
                self._reserved.discard(name)
                self._finish_at.pop(name, None)
                self._ready_at.pop(name, None)
            if reserved and wlpkg.is_evicted(wl) and self._is_plain(wl):
                self._complete_eviction(name, now)
                self._reserved.discard(name)
                self._finish_at.pop(name, None)
                self._ready_at.pop(name, None)
        for name, due in list(self._ready_at.items()):
            if due <= now:
                del self._ready_at[name]
                self._set_pods_ready(name, now)
        for name, due in list(self._finish_at.items()):
            if due <= now:
                del self._finish_at[name]
                self._finish(name, now)

    @staticmethod
    def _is_plain(wl) -> bool:
        return not wl.metadata.owner_references

    @staticmethod
    def _wl_kind(wl) -> str:
        owner = next((o for o in wl.metadata.owner_references
                      if o.controller), None)
        return owner.kind if owner is not None else "workload"

    def _arrival_for(self, wl) -> Optional[TraceArrival]:
        """The trace arrival behind a workload: direct for plain
        workloads, via the owning job object's name for job-created
        ones (the jobframework generates the workload name)."""
        owner = next((o for o in wl.metadata.owner_references
                      if o.controller), None)
        key = owner.name if owner is not None else wl.metadata.name
        return self.arrival_info.get(key)

    def _complete_eviction(self, name: str, now: float) -> None:
        """The job side of an eviction (jobframework stopJob /
        util.FinishEvictionForWorkloads): unset the reservation, set
        Requeued=False with the eviction reason."""
        store = self.mgr.store
        wl = store.try_get("Workload", "default", name)
        if wl is None:
            return
        evicted = find_condition(wl.status.conditions, api.WORKLOAD_EVICTED)
        if evicted is None or evicted.status != "True":
            return
        if wl.status.requeue_state is not None \
                and wl.status.requeue_state.requeue_at is not None:
            self.requeue_ats.append(wl.status.requeue_state.requeue_at)
        wlpkg.unset_quota_reservation_with_condition(
            wl, "Pending", "The workload was evicted", now)
        # Requeued=True immediately only for preemption/check evictions;
        # other reasons wait for their own trigger — the pods-ready
        # backoff expiry, reactivation (jobframework reconciler :443-449
        # mirrors the reference). Getting this wrong strands a
        # MultiKueue worker-lost Retry as pending-forever.
        requeue_now = evicted.reason in (api.EVICTED_BY_PREEMPTION,
                                         api.EVICTED_BY_ADMISSION_CHECK)
        wlpkg.set_requeued_condition(wl, evicted.reason, evicted.message,
                                     requeue_now, now)
        store.update(wl)

    def _set_pods_ready(self, name: str, now: float) -> None:
        wl = self.mgr.store.try_get("Workload", "default", name)
        if wl is None or not wlpkg.has_quota_reservation(wl):
            return
        set_condition(wl.status.conditions, Condition(
            type=api.WORKLOAD_PODS_READY, status="True", reason="PodsReady",
            message="All pods reached readiness"), now)
        self.mgr.store.update(wl)

    def _finish(self, name: str, now: float) -> None:
        """Mark a run complete. Plain workloads get the Finished
        condition directly; job-owned workloads are finished through
        their framework object so the real reconcile path runs."""
        store = self.mgr.store
        wl = store.try_get("Workload", "default", name)
        if wl is None or not wlpkg.has_quota_reservation(wl) \
                or wlpkg.is_finished(wl):
            return
        owner = next((o for o in wl.metadata.owner_references
                      if o.controller), None)
        if owner is None:
            set_condition(wl.status.conditions, Condition(
                type=api.WORKLOAD_FINISHED, status="True", reason="Succeeded",
                message="run complete"), now)
            store.update(wl)
            return
        _FINISHERS.get(owner.kind, _finish_noop)(store, owner.name, now)

    # -- ladder --------------------------------------------------------

    def _track_ladder(self) -> None:
        ladder = getattr(self.mgr.scheduler, "ladder", None)
        if ladder is None:
            return
        from kueue_tpu.resilience.degrade import NORMAL
        if ladder.state != NORMAL:
            self._ladder_engaged = True
        elif (self._ladder_engaged and self._ladder_recovery is None
                and self._storm_end_cycle is not None):
            self._ladder_recovery = self.cycles - self._storm_end_cycle

    # -- result assembly -----------------------------------------------

    def result(self, scale: str, slo: SLOSpec,
               tta_filter: Optional[Callable[[str], bool]] = None,
               tta_scope: str = "") -> ScenarioResult:
        """Evaluate the run against ``slo``. ``tta_filter`` narrows the
        per-class p99 population (e.g. non-storm tenants in
        tenant_storm — the storm tenant's self-inflicted queueing is
        reported in counters, not gated)."""
        res = ScenarioResult(name=self.name, seed=self.seed, scale=scale,
                             backend=_backend_info())
        res.cycles = self.cycles
        res.duration_s = self.clock.now() - self.t0
        res.submitted = self.submitted
        res.admitted = len(self.first_admit)
        res.admissions = self.admissions
        res.evictions = (self._evictions_carry
                         + self.mgr.recorder.count_by_reason_prefix(
                             "EvictedDueTo"))
        res.slo = slo

        by_class: dict = {}
        for name, tta in self.first_admit.items():
            if tta_filter is not None and not tta_filter(name):
                continue
            cls = self.class_of_wl.get(name, "standard")
            by_class.setdefault(cls, []).append(tta)
        res.class_p99_tta_s = {cls: _p99(v) for cls, v in by_class.items()}
        if tta_scope:
            res.counters["tta_scope"] = tta_scope

        # Starved = still eligible at scenario end (post-drain) without
        # a place: never admitted, OR evicted and never re-admitted (a
        # first-admission check alone would mask an eviction wave that
        # strands its victims as pending-forever — exactly the
        # MultiKueue worker-lost livelock shape).
        res.starved = [wl.metadata.name
                       for wl in self.mgr.store.list("Workload",
                                                     copy_objects=False)
                       if wlpkg.is_active(wl)
                       and not wlpkg.is_finished(wl)
                       and (wl.metadata.name not in self.first_admit
                            or not wlpkg.has_quota_reservation(wl))]

        res.restarts = self.restarts
        res.recovery_to_first_admission_s = list(self.recovery_ttas)
        if self.restarts:
            res.counters["restarts"] = self.restarts
        res.promotions = self.promotions
        res.promotion_to_first_admission_s = list(self.promotion_ttas)
        if self.promotions:
            res.counters["promotions"] = self.promotions
        if self.standby is not None:
            st = self.standby.status()
            res.counters["standby"] = {
                k: st[k] for k in ("polls", "applied_records", "resyncs",
                                   "lag_records", "max_lag_records",
                                   "fencing_epoch")}
        if res.admitted:
            res.requeue_amplification = \
                (res.admissions + res.evictions) / res.admitted
        if self._ladder_engaged:
            res.ladder_recovery_cycles = self._ladder_recovery
        else:
            res.ladder_recovery_cycles = 0

        # Journey-backed evidence (obs/journey.py + ISSUE 14): every
        # scenario reports the ledger's retention/amplification stats
        # and the live burn rates alongside its SLO verdict, so the
        # post-hoc gates and the live SLI surface stay comparable.
        led = getattr(self.mgr, "journey_ledger", None)
        if led is not None:
            st = led.status()
            res.counters["journeys"] = {
                k: st[k] for k in ("active", "completed", "requeues",
                                   "requeues_per_admission",
                                   "lru_evictions", "burn_rates")}

        # The machine-readable aging gate (obs/trend.py AgingWatch.gate
        # + ISSUE 18): every scenario result carries the same {ok,
        # failing, verdicts} contract /debug/aging serves, and an
        # SLOSpec with require_aging_green reads it in check_slo — set
        # BEFORE the check below so the gate is judged, not decorative.
        watch = getattr(self.mgr, "aging_watch", None)
        if watch is not None:
            res.counters["aging"] = watch.gate()

        res.violations = check_slo(res, slo)
        return res

    def retention_status(self) -> dict:
        """Sizes of every harness/manager structure a long-lived
        composed run (sim/soak.py) must keep bounded, in one dict so a
        soak can assert its memory SHAPE at steady state: rings at or
        under capacity, aggregates at their natural cardinality (reason
        strings, route keys), the journey ledger inside its LRU +
        exemplar caps. ``arrival_info``/``first_admit`` grow with the
        trace by design (the harness IS the outside world's memory) —
        reported so a soak can bound them against its own submit count,
        not mistaken for leaks."""
        led = getattr(self.mgr, "journey_ledger", None)
        rec = self.mgr.recorder
        fr = self.mgr.flight_recorder
        return {
            "cycle_routes": len(self.cycle_routes),
            "cycle_routes_cap": self.cycle_routes.maxlen,
            "route_mix_keys": len(self.route_mix),
            "flight_ring": len(fr.traces()),
            "flight_ring_cap": fr.capacity,
            "event_window": len(rec.events),
            "event_window_cap": rec.events.maxlen,
            "event_reason_keys": len(rec.reason_counts),
            "journeys_retained": led.retained if led is not None else 0,
            # active LRU cap + slow-exemplar heap cap + violation deque
            # cap: the hard ceiling on what the ledger may ever hold
            "journeys_retained_cap": (
                led.capacity + led.exemplars + max(4 * led.exemplars, 32)
                if led is not None else 0),
            "arrival_info": len(self.arrival_info),
            "first_admit": len(self.first_admit),
        }

    def journey_gate(self, res: ScenarioResult) -> None:
        """The ISSUE 14 acceptance gate: from /debug/journeys ALONE,
        the slowest admitted workload's journey must answer "why did it
        take N cycles" with a complete causally-stamped span timeline —
        first span ``queued``, last an admission, every span carrying a
        cycle id + generation token, time and cycle ids monotone.
        Violations land on the scenario result like any SLO breach."""
        from kueue_tpu.obs import DebugEndpoints, WorkloadJourney
        from kueue_tpu.obs.journey import JourneySpan
        led = getattr(self.mgr, "journey_ledger", None)
        if led is None:
            res.violations.append("journey gate: no ledger wired")
            return
        endpoints = DebugEndpoints(self.mgr.scheduler, self.mgr.metrics)
        payload = endpoints.handle("/debug/journeys", {"n": "1"})
        slowest = (payload or {}).get("slowest") or []
        if not slowest:
            res.violations.append(
                "journey gate: /debug/journeys retained no slowest "
                "exemplar after an admitting run")
            return
        timeline = slowest[0]
        res.counters["journey_slowest"] = {
            "workload": timeline["workload"],
            "tta_s": timeline["tta_s"],
            "spans": len(timeline["spans"]),
            "requeues": timeline["requeues"],
        }
        # Rebuild the journey from the WIRE payload (the "from
        # /debug/journeys alone" clause) and run the completeness check
        # on that, not on ledger internals.
        j = WorkloadJourney(timeline["workload"],
                            timeline["cluster_queue"], timeline["class"],
                            timeline["created_t"])
        for s in timeline["spans"]:
            j.spans.append(JourneySpan(
                s["kind"], s["t"], s["cycle"], tuple(s["generation"]),
                s.get("route", "")))
        ok, why = j.timeline_complete()
        if not ok:
            res.violations.append(
                f"journey gate: slowest exemplar "
                f"{timeline['workload']} timeline incomplete: {why}")


def _p99(values: list) -> float:
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]


# ----------------------------------------------------------------------
# object builders (one per arrival kind)
# ----------------------------------------------------------------------

def _pod_template(units: int) -> PodTemplateSpec:
    return PodTemplateSpec(spec=PodSpec(containers=[
        Container(name="c", requests={"cpu": units * UNIT})]))


def _build_workload(name, lq, arr, now):
    wl = api.Workload(metadata=ObjectMeta(
        name=name, namespace="default", uid=f"wl-{name}",
        creation_timestamp=now,
        labels={CLASS_LABEL: arr.class_name,
                TENANT_LABEL: str(arr.tenant)}))
    wl.spec.queue_name = lq
    wl.spec.priority = arr.priority
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=_pod_template(arr.request)))
    return wl


def _job_meta(name, lq, arr, now):
    return ObjectMeta(
        name=name, namespace="default", creation_timestamp=now,
        labels={api.QUEUE_LABEL: lq, CLASS_LABEL: arr.class_name,
                TENANT_LABEL: str(arr.tenant)})


def _build_job(name, lq, arr, now):
    job = batchv1.Job(metadata=_job_meta(name, lq, arr, now))
    job.spec.suspend = True
    job.spec.parallelism = 1
    job.spec.template = _pod_template(arr.request)
    return job


def _build_jobset(name, lq, arr, now):
    js = jobsetapi.JobSet(metadata=_job_meta(name, lq, arr, now))
    js.spec.suspend = True
    js.spec.replicated_jobs = [
        jobsetapi.ReplicatedJob(
            name="leader", replicas=1,
            template=batchv1.JobSpec(parallelism=1,
                                     template=_pod_template(arr.request))),
        jobsetapi.ReplicatedJob(
            name="workers", replicas=1,
            template=batchv1.JobSpec(parallelism=1,
                                     template=_pod_template(arr.request))),
    ]
    return js


def _build_pytorch(name, lq, arr, now):
    pj = kf.PyTorchJob(metadata=_job_meta(name, lq, arr, now))
    pj.spec.run_policy.suspend = True
    pj.spec.replica_specs = {
        "Master": kf.ReplicaSpec(replicas=1,
                                 template=_pod_template(arr.request)),
        "Worker": kf.ReplicaSpec(replicas=1,
                                 template=_pod_template(arr.request)),
    }
    return pj


def _build_ray(name, lq, arr, now):
    rj = rayapi.RayJob(metadata=_job_meta(name, lq, arr, now))
    rj.spec.suspend = True
    rj.spec.ray_cluster_spec = rayapi.RayClusterSpec(
        head_group_spec=rayapi.HeadGroupSpec(
            template=_pod_template(arr.request)),
        worker_group_specs=[rayapi.WorkerGroupSpec(
            group_name="workers", replicas=1,
            template=_pod_template(arr.request))])
    return rj


_BUILDERS = {
    "workload": _build_workload,
    "job": _build_job,
    "jobset": _build_jobset,
    "pytorch": _build_pytorch,
    "ray": _build_ray,
}


def _finish_job(store, name, now):
    job = store.try_get("Job", "default", name)
    if job is None:
        return
    job.status.conditions.append(Condition(
        type=batchv1.JOB_COMPLETE, status="True", message="done"))
    store.update(job)


def _finish_pytorch(store, name, now):
    pj = store.try_get("PyTorchJob", "default", name)
    if pj is None:
        return
    pj.status.conditions.append(Condition(
        type=kf.JOB_SUCCEEDED, status="True", message="done"))
    store.update(pj)


def _finish_jobset(store, name, now):
    js = store.try_get("JobSet", "default", name)
    if js is None:
        return
    js.status.conditions.append(Condition(
        type=jobsetapi.JOBSET_COMPLETED, status="True", message="done"))
    store.update(js)


def _finish_ray(store, name, now):
    rj = store.try_get("RayJob", "default", name)
    if rj is None:
        return
    rj.status.job_status = "SUCCEEDED"
    store.update(rj)


def _finish_noop(store, name, now):
    return


_FINISHERS = {
    "Job": _finish_job,
    "PyTorchJob": _finish_pytorch,
    "JobSet": _finish_jobset,
    "RayJob": _finish_ray,
}


# ----------------------------------------------------------------------
# scenario (a): diurnal wave
# ----------------------------------------------------------------------

def run_diurnal(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """Sinusoidal arrival rate with burst harmonics over round-robin
    tenants. Gates: every workload eventually admits (zero starvation
    after the drain) with bounded per-class p99 time-to-admission."""
    p = {"smoke": dict(duration=240.0, tenants=3, quota=10, base=0.12),
         "full": dict(duration=1200.0, tenants=6, quota=12, base=0.5),
         }[scale]
    h = ScenarioHarness("diurnal", seed, tenants=p["tenants"],
                        quota_units=p["quota"])
    arrivals = diurnal_trace(seed, duration_s=p["duration"],
                             tenants=p["tenants"], base_rate=p["base"])
    h.set_phase("wave")
    h.run(arrivals, p["duration"])
    h.set_phase("drain")
    h.drain()
    slo = SLOSpec(
        min_admitted=len(arrivals),
        class_max_p99_tta_s={"prod": 240.0, "standard": 480.0,
                             "batch": 900.0},
        max_requeue_amplification=1.5)
    return h.result(scale, slo)


# ----------------------------------------------------------------------
# scenario (b): tenant storm
# ----------------------------------------------------------------------

def run_tenant_storm(seed: int = 0, scale: str = "full",
                     solver: bool = False) -> ScenarioResult:
    """One LocalQueue floods while the others trickle. The cohort
    absorbs the flood through borrowing, and reclaimWithinCohort keeps
    the trickle tenants whole: the gate is zero cross-tenant starvation
    and bounded p99 time-to-admission for the NON-storm tenants (the
    storm tenant's self-inflicted backlog is reported, not gated).

    With ``solver=True`` the harness runs the production batched solver
    under the adaptive router, and the scenario additionally witnesses
    ROADMAP item 2's coverage contract UNDER REALISTIC LOAD: the
    storm's preemption-heavy cycles must route to the device (trace
    ``route`` + ``regime`` tags), not fall back to the CPU preemptor.
    The route gate is enforced only on a real device backend — on a
    CPU-fallback run the router legitimately picks whichever engine is
    faster there, so the scenario reports the route mix and records the
    refusal instead (the perf.checker cross-backend honesty policy)."""
    p = {"smoke": dict(duration=300.0, tenants=4, quota=6, storm=40),
         "full": dict(duration=900.0, tenants=8, quota=8, storm=200),
         }[scale]
    sv = None
    if solver:
        from kueue_tpu.solver import BatchSolver
        sv = BatchSolver()
    h = ScenarioHarness("tenant_storm", seed, tenants=p["tenants"],
                        quota_units=p["quota"], solver=sv)
    arrivals = storm_trace(seed, duration_s=p["duration"],
                           tenants=p["tenants"], storm_tenant=0,
                           storm_at_s=60.0, storm_count=p["storm"])
    slo = SLOSpec(
        min_admitted=len(arrivals),
        class_max_p99_tta_s={"prod": 120.0, "standard": 300.0,
                             "batch": 600.0},
        max_requeue_amplification=2.0)
    # The journey ledger prices its live SLI stream against the SAME
    # objectives this scenario gates on (ISSUE 14 burn-rate evaluator).
    h.set_objectives(slo)
    h.set_phase("trickle")
    h.run(arrivals, p["duration"],
          hooks=[(60.0, lambda: h.set_phase("storm")),
                 (75.0, h.mark_storm_end)])
    h.set_phase("drain")
    h.drain(max_cycles=240)

    def non_storm(name: str) -> bool:
        return h.tenant_of_wl.get(name) != 0
    storm_ttas = [t for n, t in h.first_admit.items()
                  if h.tenant_of_wl.get(n) == 0]
    res = h.result(scale, slo, tta_filter=non_storm,
                   tta_scope="non-storm tenants (t1..)")
    res.counters["storm_tenant_p99_tta_s"] = \
        round(_p99(storm_ttas), 3) if storm_ttas else None
    # ISSUE 14 acceptance: the slowest workload's journey, read from
    # /debug/journeys alone, must explain its N admission cycles with
    # a complete causally-stamped span timeline.
    h.journey_gate(res)
    # Route/regime coverage (trace tags stamped by set_phase): how the
    # router handled the storm's preemption-heavy cycles.
    mix: dict = {}
    for tag, route, regime in h.cycle_routes:
        if tag in ("storm", "drain"):
            key = f"{regime or 'fit'}/{route or 'none'}"
            mix[key] = mix.get(key, 0) + 1
    res.counters["storm_route_mix"] = mix
    if solver:
        preempt_cycles = sum(n for k, n in mix.items()
                             if k.startswith("preempt/"))
        # explicit device-route allowlist ('device' plus its pipelined
        # variants 'device-pipelined'/'device-dispatch-only'/
        # 'device-nofit'): a headless 'drain'/'none' step (which can
        # inherit a stale preempt regime tag) must not satisfy the
        # coverage gate
        def _is_device(route: str) -> bool:
            return route == "device" or route.startswith("device-")

        device_preempt = sum(
            n for k, n in mix.items()
            if k.startswith("preempt/") and _is_device(k.split("/")[1]))
        res.counters["storm_preempt_cycles"] = preempt_cycles
        res.counters["storm_preempt_device_cycles"] = device_preempt
        import jax
        on_device = jax.default_backend() != "cpu"
        if not on_device:
            reason = (
                "cpu backend: device-vs-cpu route economics are not the "
                "production ones; route mix recorded, gate refused")
            res.counters["route_gate_refused"] = reason
            # consolidated device-witness debt (perf.checker): a future
            # device run must witness this gate
            from kueue_tpu.perf import checker as checkerpkg
            checkerpkg.record_refusal("scenario.tenant_storm.route_gate",
                                      "device_route_gate", reason, "tpu")
        elif preempt_cycles and not device_preempt:
            res.violations.append(
                "storm preemption-heavy cycles never routed to the "
                f"device (mix: {mix}) — ROADMAP item 2 coverage gate")
    return res


# ----------------------------------------------------------------------
# scenario (c): flavor-quota churn
# ----------------------------------------------------------------------

def run_flavor_churn(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """ClusterQueue quota edits mid-traffic: every churn interval one
    CQ's nominal quota steps through a cycle (same cohort edge), which
    is exactly the single-CQ structural-epoch path — the snapshot
    maintainer must serve it via per-CQ partial rebuilds, not
    full-snapshot rebuilds, while admission stays correct (zero
    starvation, bounded p99)."""
    p = {"smoke": dict(duration=300.0, tenants=4, quota=8, interval=30.0),
         "full": dict(duration=900.0, tenants=8, quota=10, interval=20.0),
         }[scale]
    h = ScenarioHarness("flavor_churn", seed, tenants=p["tenants"],
                        quota_units=p["quota"])
    arrivals = steady_trace(seed, p["duration"], p["tenants"],
                            interval_s=25.0)
    wiggle = [0, 2, 4, 2]  # extra units over nominal, cycled per edit

    edits = {"n": 0}

    def churn():
        t = edits["n"] % p["tenants"]
        extra = wiggle[edits["n"] % len(wiggle)]
        edits["n"] += 1
        cq = h.mgr.store.get("ClusterQueue", "", f"cq-t{t}")
        cq.spec.resource_groups[0].flavors[0].resources[0].nominal_quota = \
            (p["quota"] + extra) * UNIT
        h.mgr.store.update(cq)

    hooks = [(off, churn) for off in
             _frange(p["interval"], p["duration"], p["interval"])]
    h.set_phase("churn")
    h.run(arrivals, p["duration"], hooks=hooks)
    h.set_phase("drain")
    h.drain()
    slo = SLOSpec(
        min_admitted=len(arrivals),
        class_max_p99_tta_s={"prod": 180.0, "standard": 360.0,
                             "batch": 720.0},
        max_requeue_amplification=1.5)
    res = h.result(scale, slo)
    maint = h.mgr.cache._maintainer
    res.counters["quota_edits"] = edits["n"]
    res.counters["partial_rebuilds"] = maint.partial_rebuilds if maint else 0
    res.counters["full_rebuilds"] = maint.full_rebuilds if maint else 0
    if maint is not None and maint.partial_rebuilds == 0 and edits["n"]:
        res.violations.append(
            "no per-CQ partial rebuilds recorded despite "
            f"{edits['n']} single-CQ quota edits (maintainer fell back "
            f"to {maint.full_rebuilds} full rebuilds)")
    return res


def _frange(start: float, stop: float, step: float) -> list:
    out = []
    t = start
    while t < stop:
        out.append(t)
        t += step
    return out


# ----------------------------------------------------------------------
# scenario (d): waitForPodsReady timeout flood
# ----------------------------------------------------------------------

def run_requeue_flood(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """A synchronized admission wave whose pods all miss the PodsReady
    timeout: mass eviction, then the seeded backoff jitter must
    de-synchronize the requeue storm (distinct requeue_at values, not
    one thundering herd), the degradation ladder must recover within
    its budget after the storm, and every workload must re-admit once
    pods become ready (zero starvation)."""
    p = {"smoke": dict(tenants=4, per_tenant=5, quota=8, p99=90.0),
         "full": dict(tenants=8, per_tenant=12, quota=16, p99=150.0),
         }[scale]
    cfg = cfgpkg.Configuration(
        wait_for_pods_ready=cfgpkg.WaitForPodsReady(
            enable=True, timeout_seconds=30.0, block_admission=False,
            requeuing_strategy=cfgpkg.RequeuingStrategy(
                backoff_base_seconds=10, backoff_max_seconds=120)))
    h = ScenarioHarness("requeue_flood", seed, tenants=p["tenants"],
                        quota_units=p["quota"], cfg=cfg)
    from kueue_tpu.resilience.degrade import DegradationLadder
    ladder = DegradationLadder(budget_s=60.0, shed_heads=4, survival_heads=1,
                               escalate_after=1, recovery_cycles=2,
                               ewma_alpha=1.0)
    h.mgr.scheduler.ladder = ladder

    storm = {"on": True}
    h.pods_ready_policy = \
        lambda name: None if storm["on"] else 0.0
    arrivals = burst_trace(seed, tenants=p["tenants"],
                           per_tenant=p["per_tenant"], width_s=5.0,
                           runtime_s=600.0)
    total = len(arrivals)

    def storm_on():
        # The flood makes real cycle time irrelevant in virtual time, so
        # the overload is forced the chaos_run way: a budget every cycle
        # blows, relaxed at storm end. Ladder dynamics stay deterministic.
        ladder.budget_s = 1e-9
        h.set_phase("storm")

    def storm_off():
        storm["on"] = False
        ladder.budget_s = 60.0
        # the infra issue clears: pods of everything still admitted
        # start reaching readiness
        now = h.clock.now()
        for name in list(h._reserved):
            h._ready_at.setdefault(name, now)
        h.mark_storm_end()

    h.set_phase("flood")
    h.run(arrivals, 120.0, hooks=[(10.0, storm_on), (60.0, storm_off)])
    h.set_phase("drain")
    h.drain(max_cycles=240)

    slo = SLOSpec(
        min_admitted=total,
        # the tail admits under the shed/survival head caps while the
        # ladder is engaged: p99 covers the degraded-mode queueing AND
        # the eviction+jittered-backoff lap, which stretches with scale
        # (more victims -> longer requeue tail), hence per-scale bounds
        class_max_p99_tta_s={"standard": p["p99"]},
        max_ladder_recovery_cycles=8,
        # every workload admits, evicts once, re-admits: amplification
        # ~3; headroom for a second timeout lap on stragglers
        max_requeue_amplification=4.0,
        max_evictions=2 * total)
    res = h.result(scale, slo)
    distinct = len(set(h.requeue_ats))
    spread = (max(h.requeue_ats) - min(h.requeue_ats)) if h.requeue_ats else 0.0
    res.counters["requeue_ats"] = len(h.requeue_ats)
    res.counters["requeue_at_distinct"] = distinct
    res.counters["requeue_at_spread_s"] = round(spread, 3)
    if h.requeue_ats and distinct < max(2, int(0.7 * len(h.requeue_ats))):
        res.violations.append(
            f"requeue backoff jitter failed to de-synchronize the retry "
            f"storm: {distinct} distinct requeue_at values across "
            f"{len(h.requeue_ats)} evictions")
    return res


# ----------------------------------------------------------------------
# scenario (e): MultiKueue worker-cluster loss and rejoin
# ----------------------------------------------------------------------

def run_cluster_loss(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """Workloads dispatch to two worker clusters through the MultiKueue
    admission check; mid-run one worker becomes unreachable. Reserved
    workloads there must Retry after the worker-lost timeout, re-place
    on the surviving cluster, and a rejoin must not double-dispatch
    (sticky placement deletes the stale mirror instead); orphaned
    mirrors are collected by the periodic GC. Gate: no stuck-pending
    workloads, exactly one reserving cluster per workload at the end."""
    p = {"smoke": dict(tenants=2, per_tenant=4, quota=8),
         "full": dict(tenants=4, per_tenant=10, quota=16),
         }[scale]
    cfg = cfgpkg.Configuration()
    cfg.multi_kueue.worker_lost_timeout_seconds = 30.0
    cfg.multi_kueue.gc_interval_seconds = 20.0
    h = ScenarioHarness(
        "cluster_loss", seed, tenants=p["tenants"], quota_units=p["quota"],
        cfg=cfg, mk_check=True, remote_clusters=["w1", "w2"])
    mk = h.mgr.multikueue
    arrivals = burst_trace(seed, tenants=p["tenants"],
                           per_tenant=p["per_tenant"], width_s=5.0,
                           runtime_s=10_000.0)
    total = len(arrivals)

    state: dict = {}

    def lose():
        # one local original deleted during the outage: its w1 mirror
        # becomes a true orphan only the periodic GC can collect
        on_w1 = [wl.metadata.name
                 for wl in h.mgr.store.list("Workload", copy_objects=False)
                 if mk._reserving.get(wlpkg.key(wl)) == "w1"]
        if on_w1:
            state["orphan"] = on_w1[0]
        # the rest must survive the outage by re-placing on w2
        state["survivors"] = set(on_w1[1:])
        mk.mark_cluster_lost("w1")
        h.set_phase("outage")
        if "orphan" in state:
            h.mgr.store.delete("Workload", "default", state["orphan"])
            h.arrival_info.pop(state["orphan"], None)
            h.submitted -= 1

    def rejoin():
        mk.mark_cluster_rejoined("w1")
        h.mark_storm_end()

    h.set_phase("dispatch")
    h.run(arrivals, 260.0, hooks=[(40.0, lose), (180.0, rejoin)])
    h.set_phase("drain")
    h.drain(max_cycles=240)

    slo = SLOSpec(
        min_admitted=total - (1 if "orphan" in state else 0),
        class_max_p99_tta_s={"standard": 60.0},
        max_requeue_amplification=3.0)
    res = h.result(scale, slo)

    # no-double-dispatch: every live admitted workload is reserved on
    # exactly ONE worker cluster
    double, unplaced = [], []
    w1 = h.workers["w1"]
    for wl in h.mgr.store.list("Workload", copy_objects=False):
        if not wlpkg.is_admitted(wl):
            continue
        holders = [cn for cn, worker in h.workers.items()
                   if (rw := worker.store.try_get(
                       "Workload", "default", wl.metadata.name)) is not None
                   and wlpkg.has_quota_reservation(rw)]
        if len(holders) > 1:
            double.append(wl.metadata.name)
        elif not holders:
            unplaced.append(wl.metadata.name)
    survivors = state.get("survivors", set())
    relocated = sum(1 for name in survivors
                    if mk._reserving.get(f"default/{name}") == "w2")
    res.counters["lost_with_reservation"] = len(survivors)
    res.counters["relocated"] = relocated
    res.counters["double_dispatched"] = len(double)
    res.counters["unplaced_admitted"] = len(unplaced)
    if survivors and not relocated:
        res.violations.append(
            f"worker loss stranded {len(survivors)} reserved workload(s) "
            "without a single re-placement on the surviving cluster")
    orphan = state.get("orphan")
    orphan_collected = orphan is not None and \
        w1.store.try_get("Workload", "default", orphan) is None
    res.counters["orphan_candidate"] = orphan is not None
    res.counters["orphan_collected"] = bool(orphan_collected)
    if double:
        res.violations.append(
            f"double dispatch after rejoin: {sorted(double)[:5]}")
    if unplaced:
        res.violations.append(
            f"admitted locally with no worker reservation: "
            f"{sorted(unplaced)[:5]}")
    if orphan is not None and not orphan_collected:
        res.violations.append(
            f"orphan mirror {orphan!r} survived the periodic GC")
    return res


# ----------------------------------------------------------------------
# scenario (i): MultiKueue cluster loss/rejoin mid-storm on the
# batched-column placement path (ISSUE 13)
# ----------------------------------------------------------------------

def run_cluster_rebalance(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """Cluster loss and rejoin MID-STORM with placement driven by the
    batched capacity columns (the admission cycle scores remote
    clusters inside the solve / its sequential oracle and the
    multikueue controller executes single-cluster mirrors — no
    mirror-everywhere race). One worker cluster is lost while arrivals
    keep coming: in-flight reservations there must Retry, re-score
    against the masked column and re-reserve on the survivor within the
    SLO bound; mid-outage arrivals must place directly on the survivor;
    the rejoin must not double-dispatch (sticky placement + PR-8
    probes). Gates: zero double-dispatch, bounded re-placement latency
    (SLOSpec.max_replacement_latency_s), and the batched path actually
    driving placements (planned > 0, executed > 0, zero expiries)."""
    p = {"smoke": dict(tenants=2, per_tenant=4, quota=8),
         "full": dict(tenants=4, per_tenant=10, quota=16),
         }[scale]
    cfg = cfgpkg.Configuration()
    cfg.multi_kueue.worker_lost_timeout_seconds = 30.0
    cfg.multi_kueue.gc_interval_seconds = 20.0
    h = ScenarioHarness(
        "cluster_rebalance", seed, tenants=p["tenants"],
        quota_units=p["quota"], cfg=cfg, mk_check=True,
        remote_clusters=["w1", "w2"])
    mk = h.mgr.multikueue
    arrivals = burst_trace(seed, tenants=p["tenants"],
                           per_tenant=p["per_tenant"], width_s=5.0,
                           runtime_s=10_000.0)
    # the MID-storm wave: lands during the outage, must place on w2
    arrivals += burst_trace(seed + 1, tenants=p["tenants"],
                            per_tenant=max(p["per_tenant"] // 2, 1),
                            at_s=60.0, width_s=10.0, runtime_s=10_000.0)
    arrivals.sort(key=lambda a: a.at_s)
    total = len(arrivals)

    state: dict = {}

    def lose():
        state["survivors"] = {
            wl.metadata.name
            for wl in h.mgr.store.list("Workload", copy_objects=False)
            if mk._reserving.get(wlpkg.key(wl)) == "w1"}
        state["lost_at"] = h.clock.now()
        mk.mark_cluster_lost("w1")
        h.set_phase("outage")

    def poll():
        if "lost_at" in state and "replaced_at" not in state:
            surv = state.get("survivors", set())
            if surv and all(mk._reserving.get(f"default/{n}") == "w2"
                            for n in surv):
                state["replaced_at"] = h.clock.now()

    def rejoin():
        mk.mark_cluster_rejoined("w1")
        h.set_phase("recovered")
        h.mark_storm_end()

    h.set_phase("dispatch")
    hooks = [(40.0, lose), (170.0, rejoin)]
    hooks += [(t, poll) for t in _frange(41.0, 260.0, h.cycle_s)]
    h.run(arrivals, 260.0, hooks=hooks)
    h.set_phase("drain")
    h.drain(max_cycles=240)
    poll()

    if "replaced_at" in state:
        latency = state["replaced_at"] - state["lost_at"]
    elif not state.get("survivors"):
        latency = 0.0  # nothing was reserved on w1 at loss time
    else:
        latency = None  # survivors never re-placed: SLO violation
    slo = SLOSpec(
        min_admitted=total,
        class_max_p99_tta_s={"standard": 120.0},
        max_requeue_amplification=3.5,
        # worker-lost timeout (30 virtual s) + eviction completion +
        # requeue backoff + re-admission; generous 3x headroom over the
        # protocol floor, still far inside the 260 s storm
        max_replacement_latency_s=90.0)
    res = h.result(scale, slo)
    res.replacement_latency_s = latency
    # re-evaluate the latency gate (result() ran check_slo before the
    # stamp landed)
    from kueue_tpu.perf.checker import check_slo
    res.violations = check_slo(res, slo)

    # zero double-dispatch: every admitted workload reserved on exactly
    # one worker (the PR-8 sticky-placement probes under the NEW
    # single-mirror execution path)
    double, unplaced = [], []
    for wl in h.mgr.store.list("Workload", copy_objects=False):
        if not wlpkg.is_admitted(wl):
            continue
        holders = [cn for cn, worker in h.workers.items()
                   if (rw := worker.store.try_get(
                       "Workload", "default", wl.metadata.name)) is not None
                   and wlpkg.has_quota_reservation(rw)]
        if len(holders) > 1:
            double.append(wl.metadata.name)
        elif not holders:
            unplaced.append(wl.metadata.name)
    res.counters["survivors_at_loss"] = len(state.get("survivors", ()))
    res.counters["double_dispatched"] = len(double)
    res.counters["unplaced_admitted"] = len(unplaced)
    res.counters["placements_planned"] = mk.placements_planned
    res.counters["placements_executed"] = mk.placements_executed
    res.counters["placements_expired"] = mk.placements_expired
    if double:
        res.violations.append(
            f"double dispatch after rejoin: {sorted(double)[:5]}")
    if unplaced:
        res.violations.append(
            f"admitted locally with no worker reservation: "
            f"{sorted(unplaced)[:5]}")
    if not mk.placements_planned or not mk.placements_executed:
        res.violations.append(
            "batched-column path inert: no placements planned/executed "
            f"(planned={mk.placements_planned}, "
            f"executed={mk.placements_executed})")
    return res


# ----------------------------------------------------------------------
# scenario (f): mixed job-integration traffic
# ----------------------------------------------------------------------

MIXED_KINDS = ["workload", "job", "jobset", "pytorch", "ray"]


def run_mixed_jobs(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """Job-integration reconcilers (batch Job, JobSet, PyTorchJob,
    RayJob) under the same trickle as plain Workloads, including an
    eviction lap per kind (deactivate -> framework completes the
    eviction -> reactivate -> re-admit). Gate: admission and eviction
    parity — every kind admits everything it submitted and the evicted
    sample re-admits, exactly like the plain path."""
    p = {"smoke": dict(duration=200.0, tenants=5, quota=10),
         "full": dict(duration=600.0, tenants=10, quota=12),
         }[scale]
    cfg = cfgpkg.Configuration(
        integrations=cfgpkg.Integrations(
            frameworks=list(cfgpkg.ALL_INTEGRATIONS)))
    h = ScenarioHarness("mixed_jobs", seed, tenants=p["tenants"],
                        quota_units=p["quota"], cfg=cfg)
    arrivals = steady_trace(seed, p["duration"], p["tenants"],
                            interval_s=20.0, kinds=MIXED_KINDS)
    state = {"evicted": {}}

    def evict_lap():
        # deactivate one admitted object of each kind
        picked = {}
        for wl in h.mgr.store.list("Workload", copy_objects=False):
            kind = h.kind_of_wl.get(wl.metadata.name)
            if kind is None or kind in picked:
                continue
            if wlpkg.has_quota_reservation(wl) and wlpkg.is_active(wl):
                picked[kind] = wl.metadata.name
        for kind, name in picked.items():
            wl = h.mgr.store.get("Workload", "default", name)
            wl.spec.active = False
            h.mgr.store.update(wl)
        state["evicted"] = picked
        h.set_phase("evict-lap")

    def reactivate():
        for name in state["evicted"].values():
            wl = h.mgr.store.try_get("Workload", "default", name)
            if wl is not None and not wl.spec.active:
                wl = h.mgr.store.get("Workload", "default", name)
                wl.spec.active = True
                h.mgr.store.update(wl)
        h.set_phase("steady")

    h.set_phase("steady")
    h.run(arrivals, p["duration"],
          hooks=[(p["duration"] * 0.4, evict_lap),
                 (p["duration"] * 0.4 + 40.0, reactivate)])
    h.set_phase("drain")
    h.drain(max_cycles=240)

    slo = SLOSpec(
        min_admitted=len(arrivals),
        class_max_p99_tta_s={"prod": 120.0, "standard": 240.0,
                             "batch": 480.0},
        max_requeue_amplification=1.5)
    res = h.result(scale, slo)

    submitted_by_kind: dict = {}
    for arr in h.arrival_info.values():
        submitted_by_kind[arr.kind] = submitted_by_kind.get(arr.kind, 0) + 1
    admitted_by_kind: dict = {}
    owner_kind_to_trace = {"Job": "job", "JobSet": "jobset",
                           "PyTorchJob": "pytorch", "RayJob": "ray",
                           "workload": "workload"}
    for name in h.first_admit:
        kind = owner_kind_to_trace.get(h.kind_of_wl.get(name, "workload"))
        admitted_by_kind[kind] = admitted_by_kind.get(kind, 0) + 1
    res.counters["submitted_by_kind"] = submitted_by_kind
    res.counters["admitted_by_kind"] = admitted_by_kind
    res.counters["eviction_lap"] = dict(state["evicted"])
    for kind, n in submitted_by_kind.items():
        if admitted_by_kind.get(kind, 0) < n:
            res.violations.append(
                f"admission parity broken for kind {kind!r}: "
                f"{admitted_by_kind.get(kind, 0)}/{n} admitted")
    for kind, name in state["evicted"].items():
        wl = h.mgr.store.try_get("Workload", "default", name)
        # finished is fine too: the sample re-admitted, ran, completed
        if wl is None or not (wlpkg.is_admitted(wl) or wlpkg.is_finished(wl)):
            res.violations.append(
                f"eviction parity broken for kind {kind!r}: evicted "
                f"sample {name!r} did not re-admit after reactivation")
    return res


# ----------------------------------------------------------------------
# registry
# ----------------------------------------------------------------------
# scenario (g): restart storm (crash-restart durability,
# RESILIENCE.md §6)
# ----------------------------------------------------------------------

def run_restart_storm(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """The control plane is killed at seeded mid-cycle points — an
    ``InjectedCrash`` at the ``store_write`` commit window, so some
    kills land between a WAL append and the watch event, others inside
    an admission apply — and restored from the durable checkpoint/WAL
    log each time, while steady per-tenant traffic keeps flowing.

    Gates: zero starvation after the drain (no admission lost, no
    workload stranded by a crash-orphaned in-flight decision), bounded
    per-class p99 time-to-admission (the crashes cost cycles, not
    correctness), amplification ~1 (a restore must not re-admit or
    re-evict anything the store already settled), and bounded
    recovery-to-first-admission in virtual seconds per restart."""
    import random as _random

    from kueue_tpu.resilience import faultinject
    from kueue_tpu.resilience.faultinject import FaultInjector

    p = {"smoke": dict(duration=160.0, tenants=3, quota=10,
                       interval=20.0, kills=2),
         "full": dict(duration=800.0, tenants=6, quota=12,
                      interval=12.0, kills=5),
         }[scale]
    h = ScenarioHarness("restart_storm", seed, tenants=p["tenants"],
                        quota_units=p["quota"], durable=True)
    arrivals = steady_trace(seed, duration_s=p["duration"],
                            tenants=p["tenants"],
                            interval_s=p["interval"])
    rng = _random.Random(seed ^ 0x5EED)

    def arm_kill():
        # The next crash fires at a seeded store-write hit counted from
        # NOW — deep enough to land mid-admission-wave, shallow enough
        # to fire before the next arm point replaces the schedule.
        hit = rng.randint(2, 30)
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {hit: faultinject.CRASH}}))

    # Kill points spread over the middle of the run (never during the
    # drain: the LAST restore must still prove recovery-to-first-
    # admission against live traffic).
    span = p["duration"] / (p["kills"] + 1)
    hooks = [(span * (k + 1), arm_kill) for k in range(p["kills"])]
    h.set_phase("storm")
    try:
        h.run(arrivals, p["duration"], hooks=hooks)
        h.set_phase("drain")
        h.drain()
    finally:
        faultinject.uninstall()
    slo = SLOSpec(
        min_admitted=len(arrivals),
        class_max_p99_tta_s={"prod": 240.0, "standard": 480.0,
                             "batch": 900.0},
        max_requeue_amplification=1.1,
        max_evictions=0,
        max_recovery_to_first_admission_s=6 * h.cycle_s)
    res = h.result(scale, slo)
    if h.restarts < min(1, p["kills"]):
        res.violations.append(
            f"restart storm never crashed (restarts={h.restarts}; "
            "kill schedule mis-armed?)")
    return res


# ----------------------------------------------------------------------
# scenario (j): hot-standby failover mid-storm
# (resilience/replica.py + RESILIENCE.md §7)
# ----------------------------------------------------------------------

def _usage_consistent(mgr) -> tuple:
    """Per-CQ reservation usage in the cache must equal the sum of the
    STORE's admitted workloads — the double-admission detector (a
    workload admitted by both the deposed leader and its successor
    would double-count its usage). Same cross-check tools/crash_run.py
    runs, inlined so scenarios stay self-contained."""
    expected: dict = {}
    for wl in mgr.store.list("Workload", copy_objects=False):
        if not wlpkg.has_quota_reservation(wl):
            continue
        if wlpkg.is_finished(wl) or not wlpkg.is_active(wl):
            # A finished run keeps its QuotaReserved condition in the
            # store but holds no capacity — the cache rightly dropped
            # it (crash_run's variant of this check skips the filter
            # only because its traffic never finishes).
            continue
        info = wlpkg.Info(wl)
        cq = wl.status.admission.cluster_queue
        bucket = expected.setdefault(cq, {})
        for fr, v in info.flavor_resource_usage().items():
            bucket[fr] = bucket.get(fr, 0) + v
    for cq in mgr.cache.hm.cluster_queues:
        reserved, _admitted = mgr.cache.usage_for_cluster_queue(cq)
        want = {fr: v for fr, v in expected.get(cq, {}).items() if v}
        got = {fr: v for fr, v in reserved.items() if v}
        if want != got:
            return False, f"{cq}: store says {want}, cache says {got}"
    return True, ""


def run_failover(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """The leader is killed at seeded store-write commit points while
    steady per-tenant traffic flows — and instead of the cold restore
    scenario (g) pays, a HOT STANDBY that has been tailing the WAL the
    whole time promotes: fence the dead leader's epoch, drain the
    replay tail, first cycle pinned synchronous (RESILIENCE.md §7). A
    fresh follower then shadows each promoted leader, so every kill in
    the storm fails over warm.

    Gates: promotion-to-first-admission in virtual seconds per
    promotion (SLOSpec.max_promotion_to_first_admission_s — a THIRD of
    restart_storm's cold-restore budget, the point of the warm
    follower), zero starvation after the drain, amplification ~1 and
    zero evictions (a promotion must not re-admit or re-evict anything
    the store already settled), the store-vs-cache usage cross-check
    (zero double admission across the leadership chain), and the
    fencing epoch having advanced once per promotion."""
    import random as _random

    from kueue_tpu.resilience import faultinject
    from kueue_tpu.resilience.faultinject import FaultInjector

    p = {"smoke": dict(duration=160.0, tenants=3, quota=10,
                       interval=20.0, kills=2, poll_every=1),
         "full": dict(duration=800.0, tenants=6, quota=12,
                      interval=12.0, kills=4, poll_every=2),
         }[scale]
    h = ScenarioHarness("failover", seed, tenants=p["tenants"],
                        quota_units=p["quota"], durable=True,
                        standby=True,
                        standby_poll_every=p["poll_every"])
    arrivals = steady_trace(seed, duration_s=p["duration"],
                            tenants=p["tenants"],
                            interval_s=p["interval"])
    rng = _random.Random(seed ^ 0xFA110)

    def arm_kill():
        # Seeded store-write kill counted from NOW — deep enough to
        # land mid-admission-wave, shallow enough to fire before the
        # next arm point replaces the schedule.
        hit = rng.randint(2, 30)
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {hit: faultinject.CRASH}}))

    span = p["duration"] / (p["kills"] + 1)
    hooks = [(span * (k + 1), arm_kill) for k in range(p["kills"])]
    h.set_phase("storm")
    try:
        h.run(arrivals, p["duration"], hooks=hooks)
        h.set_phase("drain")
        h.drain()
    finally:
        faultinject.uninstall()
    slo = SLOSpec(
        min_admitted=len(arrivals),
        class_max_p99_tta_s={"prod": 240.0, "standard": 480.0,
                             "batch": 900.0},
        max_requeue_amplification=1.1,
        max_evictions=0,
        # restart_storm's cold budget is 6 cycles; the warm follower
        # must beat it decisively.
        max_promotion_to_first_admission_s=2 * h.cycle_s)
    res = h.result(scale, slo)
    if h.promotions < min(1, p["kills"]):
        res.violations.append(
            f"failover storm never promoted (promotions="
            f"{h.promotions}; kill schedule mis-armed?)")
    ok, msg = _usage_consistent(h.mgr)
    if not ok:
        res.violations.append(f"double-admission detector: {msg}")
    # One epoch per leadership change: the initial lead() takes 1 and
    # each promotion bumps once — anything else means a fencing hole.
    want_epoch = 1 + h.promotions
    if h.durable.fencing_epoch != want_epoch:
        res.violations.append(
            f"fencing epoch {h.durable.fencing_epoch} != "
            f"{want_epoch} (1 initial lease + {h.promotions} "
            f"promotion(s))")
    res.counters["fencing_epoch"] = h.durable.fencing_epoch
    return res


# ----------------------------------------------------------------------
# scenario (h): query-plane read storm under admission traffic
# (obs/queryplane.py + ISSUE 12)
# ----------------------------------------------------------------------

def run_visibility_storm(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """Reader threads hammer the snapshot-backed query plane — pending
    positions per CQ/LQ plus point status queries — CONCURRENTLY with
    steady admission traffic and mid-run single-CQ quota churn (the
    structural edits that move the generation token, so the staleness
    gate is non-vacuous).

    Gates: the usual zero-starvation/p99 bounds on the admission side
    (reads must not break admission), plus the read-plane contract —
    every response internally consistent (one immutable table per
    sealed view, duplicate-free, dense per-LQ positions), a floor on
    reads actually served, and the worst response-token lag vs the live
    cache bounded at ONE structural generation (a sealed view lags only
    between an edit and the next cycle seal)."""
    import threading as _threading

    p = {"smoke": dict(duration=240.0, tenants=4, quota=8, interval=60.0,
                       readers=2),
         "full": dict(duration=900.0, tenants=8, quota=10, interval=45.0,
                      readers=4),
         }[scale]
    h = ScenarioHarness("visibility_storm", seed, tenants=p["tenants"],
                        quota_units=p["quota"])
    plane = h.mgr.query_plane
    assert plane is not None, "query plane disabled in manager config"
    arrivals = steady_trace(seed, p["duration"], p["tenants"],
                            interval_s=20.0)

    # Mid-run structural churn: one CQ's nominal quota wiggles (the
    # flavor_churn single-CQ epoch path) so response tokens must chase
    # a moving generation.
    edits = {"n": 0}

    def churn():
        t = edits["n"] % p["tenants"]
        extra = (edits["n"] % 3)  # 0/1/2 extra units, cycled
        edits["n"] += 1
        cq = h.mgr.store.get("ClusterQueue", "", f"cq-t{t}")
        cq.spec.resource_groups[0].flavors[0].resources[0].nominal_quota = \
            (p["quota"] + extra) * UNIT
        h.mgr.store.update(cq)
        h.mgr.run_until_idle()
        note_driver_lag()  # an un-sealed edit: the view lags <= 1

    stop = _threading.Event()
    stats = {"reads": 0, "warming": 0, "max_lag": None, "errors": []}
    stats_lock = _threading.Lock()
    # The GATED staleness bound is measured deterministically from the
    # driver thread (the plane's actual guarantee: the CURRENT view
    # lags at most the edits since its seal). Reader-side lag samples
    # additionally ride a hold-window race — a reader descheduled
    # between acquire and its lag read can observe an extra
    # edit+seal+edit — so they get their own looser sanity bound below
    # instead of feeding the SLO gate flakily.
    driver_lag = {"max": None}

    def note_driver_lag():
        lag = h.mgr.query_plane.token_lag()
        if lag is not None and (driver_lag["max"] is None
                                or lag > driver_lag["max"]):
            driver_lag["max"] = lag

    def read_once(n: int) -> bool:
        """One validated plane read (shared by the concurrent reader
        threads AND the driver's deterministic tail batch). Returns
        False while the plane is still warming."""
        cache = h.mgr.cache
        view = plane.acquire()
        if view is None:
            with stats_lock:
                stats["warming"] += 1
            return False
        try:
            # staleness sampled AT ACQUIRE: the bound under test is
            # how stale a just-acquired view can be, not how far a
            # long-held borrow can drift
            lag = cache.generation_lag(view.generation)
            cq_name = f"cq-t{n % p['tenants']}"
            rows = plane.pending_cq(view, cq_name, 100, 0)
            err = _check_rows(rows)
            again = plane.pending_cq(view, cq_name, 100, 0)
            if [r.name for r in again] != [r.name for r in rows]:
                err = err or (f"{cq_name}: two reads of one sealed "
                              f"view disagreed (torn table)")
            if n % 7 == 0 and rows:
                st = plane.workload_status(view, rows[0].namespace,
                                           rows[0].name)
                if not st["found"]:
                    err = err or (f"{rows[0].name} pending in the "
                                  f"table but status not found")
            with stats_lock:
                stats["reads"] += 1
                if stats["max_lag"] is None or lag > stats["max_lag"]:
                    stats["max_lag"] = lag
                if err and len(stats["errors"]) < 5:
                    stats["errors"].append(err)
        finally:
            plane.release(view)
        return True

    def reader(idx: int) -> None:
        import time as _real_time
        n = idx
        while not stop.is_set():
            if not read_once(n):
                _real_time.sleep(0.001)
                continue
            n += 1
            if n % 64 == 0:
                _real_time.sleep(0)  # let the scheduler thread run
        # post-loop: nothing — borrows all returned via finally

    def _check_rows(rows) -> Optional[str]:
        names = [r.name for r in rows]
        if len(set(names)) != len(names):
            return f"duplicate rows in one table: {names}"
        by_lq: dict = {}
        for r in rows:
            lqk = f"{r.namespace}/{r.local_queue_name}"
            expect = by_lq.get(lqk, 0)
            if r.position_in_local_queue != expect:
                return (f"LQ positions not dense for {lqk}: got "
                        f"{r.position_in_local_queue}, want {expect}")
            by_lq[lqk] = expect + 1
        return None

    threads = [_threading.Thread(target=reader, args=(i,), daemon=True)
               for i in range(p["readers"])]
    for t in threads:
        t.start()
    # Sample the deterministic staleness bound after EVERY step (a
    # seal must catch the view back up to the live token).
    orig_step = h.step

    def step_and_note():
        orig_step()
        note_driver_lag()

    h.step = step_and_note
    try:
        hooks = [(off, churn) for off in
                 _frange(p["interval"], p["duration"], p["interval"])]
        h.set_phase("storm")
        h.run(arrivals, p["duration"], hooks=hooks)
        h.set_phase("drain")
        h.drain()
        # Deterministic tail: the reads floor must not depend on how
        # much wall time the OS gave the reader threads (a starved
        # sub-second smoke run could serve a handful) — the driver
        # issues a full validated batch through the same read path.
        for k in range(60):
            read_once(k)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)

    slo = SLOSpec(
        min_admitted=len(arrivals),
        class_max_p99_tta_s={"prod": 240.0, "standard": 480.0,
                             "batch": 900.0},
        max_requeue_amplification=1.5,
        min_reads=50,
        max_read_staleness_generations=1)
    res = h.result(scale, slo)
    # result() computed violations before the read stats landed on the
    # result; re-evaluate with them present. The gated staleness bound
    # is the DRIVER-measured one (deterministic); the reader-observed
    # max carries a hold-window race allowance of one extra
    # edit+seal+edit and gets its own sanity bound.
    res.reads = stats["reads"]
    res.read_staleness_generations = driver_lag["max"]
    res.violations = check_slo(res, slo)
    res.counters["reads"] = stats["reads"]
    res.counters["warming_reads"] = stats["warming"]
    res.counters["quota_edits"] = edits["n"]
    res.counters["tables_built"] = plane.tables_built
    res.counters["cycles_published"] = plane.cycles_published
    res.counters["max_reader_observed_lag"] = stats["max_lag"]
    if stats["max_lag"] is not None and stats["max_lag"] > 2:
        res.violations.append(
            f"reader-observed token lag {stats['max_lag']} exceeds the "
            "hold-window allowance of 2 (one edit+seal+edit past the "
            "deterministic bound)")
    for err in stats["errors"]:
        res.violations.append(f"read consistency: {err}")
    # Reader-held handouts all returned: after shutdown the leak
    # detector must read zero (the ISSUE 12 satellite regression,
    # exercised here under a real concurrent read storm).
    h.mgr.shutdown(checkpoint=False)
    if h.mgr.cache.live_handouts != 0:
        res.violations.append(
            f"{h.mgr.cache.live_handouts} snapshot handout(s) leaked "
            "by the read storm (live_handouts != 0 after shutdown)")
    return res


# ----------------------------------------------------------------------
# scenario (k): composed multi-day soak (sim/soak.py + ISSUE 18)
# ----------------------------------------------------------------------

def _run_soak(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """Lazy wrapper: soak.py composes THIS module's harness, so the
    import runs at call time, not at catalog definition."""
    from kueue_tpu.sim.soak import run_soak_scenario
    return run_soak_scenario(seed=seed, scale=scale)


# ----------------------------------------------------------------------
# scenarios (l, m): sharded admission control plane (sim/shardstorm.py
# + ISSUE 20 / RESILIENCE.md §9) — lazy for the same reason as soak.
# ----------------------------------------------------------------------

def _run_shard_storm(seed: int = 0, scale: str = "full") -> ScenarioResult:
    from kueue_tpu.sim.shardstorm import run_shard_storm
    return run_shard_storm(seed=seed, scale=scale)


def _run_shard_rebalance(seed: int = 0,
                         scale: str = "full") -> ScenarioResult:
    from kueue_tpu.sim.shardstorm import run_shard_rebalance
    return run_shard_rebalance(seed=seed, scale=scale)


# ----------------------------------------------------------------------

SCENARIOS = {
    "diurnal": run_diurnal,
    "tenant_storm": run_tenant_storm,
    "flavor_churn": run_flavor_churn,
    "requeue_flood": run_requeue_flood,
    "cluster_loss": run_cluster_loss,
    "cluster_rebalance": run_cluster_rebalance,
    "mixed_jobs": run_mixed_jobs,
    "restart_storm": run_restart_storm,
    "failover": run_failover,
    "visibility_storm": run_visibility_storm,
    "soak": _run_soak,
    "shard_storm": _run_shard_storm,
    "shard_rebalance": _run_shard_rebalance,
}

# Names above are the BUILT-IN catalog; adversarial repro specs
# (sim/adversary.py register_repro) add entries at runtime so a
# minimized failing trace replays through the same run_scenario path.
BUILTIN_SCENARIOS = tuple(sorted(SCENARIOS))


def list_scenarios() -> list:
    return sorted(SCENARIOS)


def run_scenario(name: str, seed: int = 0, scale: str = "full",
                 solver: bool = False) -> ScenarioResult:
    if name not in SCENARIOS:
        raise KeyError(f"unknown scenario {name!r}; "
                       f"one of {', '.join(list_scenarios())}")
    if scale not in ("smoke", "full"):
        raise ValueError(f"scale must be 'smoke' or 'full', got {scale!r}")
    fn = SCENARIOS[name]
    if solver:
        # only scenarios that grew a solver-coverage gate accept the
        # kwarg (run_tenant_storm's ROADMAP-item-2 device-route gate);
        # asking for it elsewhere is an operator error, not a silent
        # no-op
        import inspect
        if "solver" not in inspect.signature(fn).parameters:
            raise ValueError(
                f"scenario {name!r} has no solver mode; "
                f"solver-gated scenarios: tenant_storm")
        return fn(seed=seed, scale=scale, solver=True)
    return fn(seed=seed, scale=scale)
