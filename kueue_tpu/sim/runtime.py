"""Deterministic controller runtime: workqueues drained to idle.

Replaces controller-runtime's manager (reference: cmd/kueue/main.go:141,
pkg/controller/core/core.go:36). Each Controller owns a rate-unlimited
workqueue of reconcile keys; `Runtime.run_until_idle()` drains every
queue round-robin until no work remains, which makes integration-style
tests deterministic (the reference gets the same effect from gomega
Eventually loops over envtest).

Delayed requeues (`RequeueAfter`) are held in a time-ordered list and
released by `advance()` against the injected clock — the analogue of the
reference's fake-clock-driven requeue-backoff tests
(workload_controller.go:486-552).
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field
from typing import Callable, Optional

from kueue_tpu.api.meta import Clock, REAL_CLOCK


@dataclass
class Event:
    object_key: str
    kind: str
    type: str  # "Normal" | "Warning"
    reason: str
    message: str


# Default retained-event window. Million-event scenario runs (the
# sim/scenarios.py traffic suites) would otherwise grow the event list
# without bound; 100k keeps every test-scale run fully retained while
# bounding a storm's memory to the recent window.
DEFAULT_EVENT_CAPACITY = 100_000


class EventRecorder:
    """record.EventRecorder stand-in; events are assertions targets in
    tests.

    Bounded: the retained ``events`` window is a ring of the last
    ``capacity`` events (oldest dropped first), while ``reason_counts``
    and ``total_events`` keep exact lifetime tallies — so a
    million-event scenario run can still assert on eviction/requeue
    *counts* after the early events have rotated out. ``by_reason``
    operates on the retained window only."""

    def __init__(self, capacity: int = DEFAULT_EVENT_CAPACITY):
        if capacity < 1:
            raise ValueError("event recorder capacity must be >= 1")
        from collections import deque
        self.capacity = capacity
        self.events: "deque[Event]" = deque(maxlen=capacity)
        self.reason_counts: dict = {}   # reason -> lifetime count
        self.total_events = 0

    def _record(self, event: Event) -> None:
        self.events.append(event)   # deque(maxlen): oldest falls off
        self.total_events += 1
        self.reason_counts[event.reason] = \
            self.reason_counts.get(event.reason, 0) + 1

    def event(self, obj, etype: str, reason: str, message: str) -> None:
        meta = obj.metadata
        key = f"{meta.namespace}/{meta.name}" if meta.namespace else meta.name
        self._record(Event(key, type(obj).__name__, etype, reason, message))

    def system_event(self, etype: str, reason: str, message: str) -> None:
        """An event about the control plane itself rather than a stored
        object (device faults, breaker trips/recoveries): no object key,
        kind "Scheduler" — chaos tooling and operators read the outage
        timeline from these."""
        self._record(Event("", "Scheduler", etype, reason, message))

    def by_reason(self, reason: str) -> list[Event]:
        """Matching events within the retained window (use
        ``reason_counts`` for exact lifetime tallies)."""
        return [e for e in self.events if e.reason == reason]

    def count_by_reason_prefix(self, prefix: str) -> int:
        """Lifetime count of events whose reason starts with ``prefix``
        (e.g. "EvictedDueTo" sums every eviction reason) — survives ring
        rotation, so scenario SLO gates read amplification from here."""
        return sum(n for r, n in self.reason_counts.items()
                   if r.startswith(prefix))


class Controller:
    """One reconciler + its workqueue. reconcile(key) may return a float
    (requeue-after seconds), True (immediate requeue), or None."""

    def __init__(self, name: str, reconcile: Callable[[str], object]):
        self.name = name
        self._reconcile = reconcile
        from collections import deque
        self._queue: "deque[str]" = deque()  # deque: popleft is O(1)
        self._queued: set[str] = set()

    def enqueue(self, key: str) -> None:
        if key not in self._queued:
            self._queued.add(key)
            self._queue.append(key)

    def has_work(self) -> bool:
        return bool(self._queue)

    def process_one(self) -> object:
        key = self._queue.popleft()
        self._queued.discard(key)
        return key, self._reconcile(key)


class Runtime:
    def __init__(self, clock: Clock = REAL_CLOCK, metrics=None):
        self.clock = clock
        self.controllers: list[Controller] = []
        self._timer_seq = itertools.count()
        self._timers: list = []  # heap of (due, seq, controller, key)
        # Optional metrics Registry: every reconcile's wall seconds land
        # in reconcile_seconds{controller} — the coarse latency signal
        # for the wall_s - cycle_time_total gap the scheduler-only
        # flight recorder can't see (ROADMAP PR-4 follow-up).
        self.metrics = metrics

    def add_controller(self, ctrl: Controller) -> Controller:
        self.controllers.append(ctrl)
        return ctrl

    def controller(self, name: str, reconcile: Callable[[str], object]) -> Controller:
        return self.add_controller(Controller(name, reconcile))

    def requeue_after(self, ctrl: Controller, key: str, delay: float) -> None:
        heapq.heappush(self._timers,
                       (self.clock.now() + delay, next(self._timer_seq), ctrl, key))

    def _release_due_timers(self) -> None:
        now = self.clock.now()
        while self._timers and self._timers[0][0] <= now:
            _, _, ctrl, key = heapq.heappop(self._timers)
            ctrl.enqueue(key)

    def run_until_idle(self, max_iterations: int = 10000) -> int:
        """Drain every controller queue in registration order; returns
        the reconcile count. Raises if the system does not settle (a
        reconcile hot-loop).

        Each pass drains a controller's CURRENT queue fully (bounded by
        its length at pass start, so immediate requeues go to the next
        pass) before moving on. Order matters for throughput, not
        correctness: the workload controller registers first, so all of
        an admission wave's workload-event echoes land — deduping into
        ONE ClusterQueue/LocalQueue key each — before the status
        reconcilers run, instead of interleaving and rebuilding each CQ
        status several times per cycle."""
        import time as _time
        processed = 0
        metrics = self.metrics
        self._release_due_timers()
        for _ in range(max_iterations):
            worked = False
            for ctrl in self.controllers:
                for _ in range(len(ctrl._queue)):
                    worked = True
                    if metrics is not None:
                        t0 = _time.perf_counter()
                        key, result = ctrl.process_one()
                        metrics.reconcile_observed(
                            ctrl.name, _time.perf_counter() - t0)
                    else:
                        key, result = ctrl.process_one()
                    processed += 1
                    if result is True:
                        ctrl.enqueue(key)
                    elif isinstance(result, (int, float)) \
                            and result is not False and result > 0:
                        self.requeue_after(ctrl, key, float(result))
            if not worked:
                return processed
        raise RuntimeError("runtime did not settle: reconcile hot-loop suspected")

    def advance(self, dt: float, fake_clock=None) -> int:
        """Advance the fake clock, release due timers, drain to idle.
        With a real clock (no .advance), just releases anything already
        due — wall time moves on its own."""
        clk = fake_clock if fake_clock is not None else self.clock
        if hasattr(clk, "advance"):
            clk.advance(dt)
        self._release_due_timers()
        return self.run_until_idle()

    def next_timer_due(self) -> Optional[float]:
        return self._timers[0][0] if self._timers else None
