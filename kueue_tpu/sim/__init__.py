"""In-process simulated apiserver + controller runtime.

The reference runs against a real kube-apiserver (unit tests use
controller-runtime fake clients; integration tests use envtest —
/root/reference/test/integration/framework/framework.go). This package is
the equivalent substrate for the TPU-native build, per SURVEY.md §4: an
in-memory object store with watch events, finalizer-aware deletion and
resource-version bumping, plus a deterministic controller runtime
(workqueues drained to idle) replacing controller-runtime's manager.
"""

from kueue_tpu.sim.store import (
    Invalid,
    ADDED,
    DELETED,
    MODIFIED,
    Conflict,
    NotFound,
    AlreadyExists,
    Store,
    kind_of,
    obj_key,
)
from kueue_tpu.sim.durable import (DurableLog, Fenced, LoadParts,
                                   LoadResult, TailCursor)
from kueue_tpu.sim.runtime import Controller, EventRecorder, Runtime

__all__ = [
    "ADDED", "MODIFIED", "DELETED",
    "Store", "NotFound", "AlreadyExists", "Conflict", "Invalid",
    "kind_of", "obj_key",
    "DurableLog", "LoadResult", "LoadParts", "TailCursor", "Fenced",
    "Controller", "Runtime", "EventRecorder",
]
