"""Virtual-time soak harness: the scenario catalog composed into one
multi-day schedule on ONE long-lived control plane (ISSUE 18 /
ROADMAP item 4).

Every catalog scenario (sim/scenarios.py) exercises one storm shape on
a fresh manager and tears it down minutes later; the failure modes
that killed real control planes are the ones that need DAYS of
composed traffic to surface — a leak that only shows after the third
diurnal wave, a requeue pile-up seeded by a quota edit two phases
earlier, a failover landing on a process already aged by a cluster
outage. This module runs that composition: diurnal waves into quota
churn into cluster loss into a readiness outage into a crash (cold
restore) into a MID-STORM failover (hot-standby promotion), all on one
``ScenarioHarness``/DurableLog/FakeClock, with phase tags on every
cycle trace and the AgingWatch sampled at every cycle seal.

The soak verdict is one ``check_slo`` call over the composed run's
ScenarioResult, gated on (SLOSpec soak fields, perf/checker.py):

- the AgingWatch ending GREEN (``require_aging_green`` reads the
  ``counters["aging"]`` gate dict — no monitor ``leaking`` or
  ``over-bound`` at run end);
- zero mid-traffic compiles after virtual day 1
  (``max_mid_traffic_compiles_after_warm=0``; solver-less runs stamp
  an honest 0);
- bounded journey SLO burn rate per class
  (``max_journey_burn_rate``);
- zero live snapshot handouts at teardown
  (``require_zero_live_handouts``, stamped after manager shutdown);

plus the usual queueing gates (zero starvation, bounded per-class p99
TTA, bounded requeue amplification) and the soak's own structural
checks: the schedule actually crashed AND failed over, and every
bounded harness structure (retention_status) stayed inside its cap.

Deterministic per (params, seed): virtual time only, seeded traces,
seeded kill points. ``SoakParams`` is the FULL parameter surface —
serializable, so the adversarial search (sim/adversary.py) can mutate
it, shrink a failing trace, and emit the minimum as a named scenario
spec the catalog replays.

Registered in the catalog as scenario ``soak`` (smoke = the sub-second
tier-1 composition, full = the multi-day acceptance schedule).
"""

from __future__ import annotations

import random
from dataclasses import asdict, dataclass, field, fields

from kueue_tpu import config as cfgpkg
from kueue_tpu.perf.checker import SLOSpec, check_slo
from kueue_tpu.sim.scenarios import (ScenarioHarness, ScenarioResult,
                                     UNIT, _frange)
from kueue_tpu.sim.traces import burst_trace, diurnal_trace, steady_trace


@dataclass
class SoakParams:
    """The composed schedule's full parameter surface. Every knob the
    adversary may mutate lives here — arrival mix, burst harmonics,
    churn cadence, outage geometry, readiness-storm shape, kill-site
    windows — so a failing trace is replayable from (params, seed)
    alone and shrinkable one dimension at a time.

    A virtual "day" is ``day_s`` seconds of the FakeClock; the
    schedule runs ``days`` of them (minimum 3): days 1..N-2 are the
    diurnal wave, day N-1 is churn -> cluster outage -> readiness
    storm, day N is crash-storm -> failover-storm, then the drain."""

    # horizon / clock
    days: int = 3
    day_s: float = 240.0
    cycle_s: float = 5.0
    # cluster shape
    tenants: int = 3
    quota_units: int = 10
    # diurnal wave (sim/traces.py diurnal_trace)
    base_rate: float = 0.05        # arrivals/s at the sinusoid's mean
    amplitude: float = 0.8
    burst_extra: float = 0.15      # burst harmonic height, arrivals/s
    burst_width_frac: float = 0.05  # of the diurnal period
    # background trickle on the storm days
    trickle_interval_s: float = 40.0
    # quota churn cadence (fraction of day_s between single-CQ edits)
    churn_interval_frac: float = 0.08
    churn_wiggle: tuple = (0, 2, 4, 2)   # extra quota units, cycled
    # worker-cluster outage (MultiKueue w1 loss -> rejoin)
    outage_start_frac: float = 0.15      # into the outage phase
    outage_end_frac: float = 0.75
    # synchronized storm shape (readiness / crash / failover phases)
    storm_per_tenant: int = 4
    storm_width_s: float = 5.0
    storm_runtime_s: float = 60.0
    # pods-ready outage inside the readiness phase: admitted pods stay
    # NotReady this long (0 disables the readiness storm — the default
    # composed soak keeps it off; the adversary turns it up)
    pods_ready_outage_s: float = 0.0
    # waitForPodsReady config (the planted-weakness slot: an
    # undersized backoff_max_s is the fixture weakness the adversarial
    # search must find traffic to expose)
    pods_ready_timeout_s: float = 30.0
    backoff_base_s: float = 10.0
    backoff_max_s: float = 120.0
    # seeded kill window (store-write hit counts, crash_run idiom)
    kill_hit_lo: int = 2
    kill_hit_hi: int = 30
    # MultiKueue timings
    worker_lost_timeout_s: float = 30.0
    gc_interval_s: float = 20.0

    def to_dict(self) -> dict:
        d = asdict(self)
        d["churn_wiggle"] = list(self.churn_wiggle)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SoakParams":
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown SoakParams key(s): {sorted(unknown)}")
        kw = dict(d)
        if "churn_wiggle" in kw:
            kw["churn_wiggle"] = tuple(kw["churn_wiggle"])
        return cls(**kw)


# Catalog presets: ``smoke`` must stay sub-second (it rides tier-1 CI);
# ``full`` is the multi-day acceptance schedule — three real virtual
# days at a 60 s cycle cadence.
PRESETS = {
    "smoke": SoakParams(),
    "full": SoakParams(days=3, day_s=86_400.0, cycle_s=60.0, tenants=4,
                       quota_units=12, base_rate=0.004, burst_extra=0.02,
                       trickle_interval_s=600.0, churn_interval_frac=0.02,
                       storm_per_tenant=8, storm_width_s=30.0,
                       storm_runtime_s=600.0),
}


@dataclass
class SoakPhase:
    """One leg of the composed schedule: a phase tag for the cycle
    traces, a duration, its arrivals (at_s relative to phase start)
    and its hooks ((at_s, fn), same contract as ScenarioHarness.run)."""
    name: str
    duration_s: float
    arrivals: list = field(default_factory=list)
    hooks: list = field(default_factory=list)


def _soak_cfg(params: SoakParams) -> cfgpkg.Configuration:
    cfg = cfgpkg.Configuration(
        wait_for_pods_ready=cfgpkg.WaitForPodsReady(
            enable=True, timeout_seconds=params.pods_ready_timeout_s,
            block_admission=False,
            requeuing_strategy=cfgpkg.RequeuingStrategy(
                backoff_base_seconds=params.backoff_base_s,
                backoff_max_seconds=params.backoff_max_s)))
    cfg.multi_kueue.worker_lost_timeout_seconds = \
        params.worker_lost_timeout_s
    cfg.multi_kueue.gc_interval_seconds = params.gc_interval_s
    return cfg


def build_phases(h: ScenarioHarness, params: SoakParams,
                 rng: random.Random, state: dict) -> list:
    """The composed schedule against a live harness. ``state`` is the
    cross-phase scratchpad the run loop and the verdict read
    (compile-counter warm snapshot, readiness bookkeeping)."""
    from kueue_tpu.resilience import faultinject
    from kueue_tpu.resilience.faultinject import FaultInjector

    p = params
    days = max(3, p.days)
    seed = h.seed

    def arm_kill() -> None:
        # crash_run's sweep idiom generalized: the next store write
        # numbered in [lo, hi] from NOW dies. Seeded, so the kill point
        # is part of the replayable trace.
        hit = rng.randint(p.kill_hit_lo, max(p.kill_hit_lo, p.kill_hit_hi))
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {hit: faultinject.CRASH}}))

    phases = []

    # --- days 1..N-2: the diurnal wave -------------------------------
    wave_s = (days - 2) * p.day_s
    period = p.day_s / 2.0
    bursts = [(period * (k + 0.25), period * p.burst_width_frac,
               p.burst_extra) for k in range(max(1, int(wave_s / period)))]
    wave = diurnal_trace(seed, duration_s=wave_s, tenants=p.tenants,
                         base_rate=p.base_rate, amplitude=p.amplitude,
                         period_s=period, bursts=bursts)
    # Warm horizon = end of virtual day 1: the compile-storm gate
    # counts only variants first executed AFTER this snapshot.
    def mark_warm() -> None:
        state["compiles_at_warm"] = _compiles(h)
    phases.append(SoakPhase("wave", wave_s, wave,
                            hooks=[(p.day_s, mark_warm)]))

    # --- day N-1 part 1: quota churn ---------------------------------
    churn_s = 0.4 * p.day_s
    churn_arrivals = steady_trace(seed + 1, churn_s, p.tenants,
                                  interval_s=p.trickle_interval_s)
    edits = state.setdefault("quota_edits", {"n": 0})

    def churn() -> None:
        t = edits["n"] % p.tenants
        extra = p.churn_wiggle[edits["n"] % len(p.churn_wiggle)]
        edits["n"] += 1
        cq = h.mgr.store.get("ClusterQueue", "", f"cq-t{t}")
        cq.spec.resource_groups[0].flavors[0].resources[0].nominal_quota = \
            (p.quota_units + extra) * UNIT
        h.mgr.store.update(cq)

    interval = max(h.cycle_s, p.churn_interval_frac * p.day_s)
    phases.append(SoakPhase(
        "churn", churn_s, churn_arrivals,
        hooks=[(off, churn) for off in _frange(interval, churn_s,
                                               interval)]))

    # --- day N-1 part 2: worker-cluster outage -----------------------
    outage_s = 0.3 * p.day_s
    outage_arrivals = steady_trace(seed + 2, outage_s, p.tenants,
                                   interval_s=p.trickle_interval_s)

    def lose() -> None:
        # h.mgr may have been replaced by a restore by the time a hook
        # fires — re-read the controller handle, never capture it
        h.mgr.multikueue.mark_cluster_lost("w1")

    def rejoin() -> None:
        h.mgr.multikueue.mark_cluster_rejoined("w1")
    phases.append(SoakPhase(
        "outage", outage_s, outage_arrivals,
        hooks=[(p.outage_start_frac * outage_s, lose),
               (p.outage_end_frac * outage_s, rejoin)]))

    # --- day N-1 part 3: readiness storm -----------------------------
    # A synchronized same-class wave whose pods stay NotReady for the
    # outage window: every victim laps through PodsReady timeout ->
    # eviction -> jittered backoff -> re-admission until readiness
    # returns. THIS is the phase whose shape the adversary tunes
    # against an undersized backoff bound. Disabled (trickle only)
    # when the outage window or the storm size is zero.
    # The phase stretches to CONTAIN the outage (plus recovery head-
    # room): a weak backoff's laps accumulate linearly with the outage
    # length, which is exactly the dose-response the adversary probes.
    ready_s = max(0.3 * p.day_s, 1.25 * p.pods_ready_outage_s)
    ready_arrivals = steady_trace(seed + 3, ready_s, p.tenants,
                                  interval_s=p.trickle_interval_s)
    hooks = []
    if p.pods_ready_outage_s > 0 and p.storm_per_tenant > 0:
        ready_arrivals += burst_trace(
            seed + 4, tenants=p.tenants, per_tenant=p.storm_per_tenant,
            at_s=0.0, width_s=p.storm_width_s,
            runtime_s=p.storm_runtime_s)
        ready_arrivals.sort(key=lambda a: a.at_s)

        def not_ready_on() -> None:
            state["pods_down"] = True

        def not_ready_off() -> None:
            state["pods_down"] = False
            # the infra issue clears: pods of everything still admitted
            # start reaching readiness (requeue_flood's storm_off)
            now = h.clock.now()
            for name in list(h._reserved):
                h._ready_at.setdefault(name, now)
        hooks = [(0.0, not_ready_on),
                 (min(p.pods_ready_outage_s, ready_s), not_ready_off)]
    phases.append(SoakPhase("readiness", ready_s, ready_arrivals, hooks))

    # --- day N part 1: crash storm (cold restore) --------------------
    crash_s = 0.5 * p.day_s
    crash_arrivals = steady_trace(seed + 5, crash_s, p.tenants,
                                  interval_s=p.trickle_interval_s)
    crash_arrivals += burst_trace(
        seed + 6, tenants=p.tenants,
        per_tenant=max(1, p.storm_per_tenant // 2), at_s=0.0,
        width_s=p.storm_width_s, runtime_s=p.storm_runtime_s)
    crash_arrivals.sort(key=lambda a: a.at_s)
    phases.append(SoakPhase("crash-storm", crash_s, crash_arrivals,
                            hooks=[(0.25 * crash_s, arm_kill)]))

    # --- day N part 2: mid-storm failover ----------------------------
    # The standby is enabled LIVE (replica.lead + a warm follower
    # tailing the WAL) on the already-aged plane, a storm lands, and
    # the leader is killed mid-storm: the next crash must PROMOTE, not
    # cold-restore.
    fail_s = 0.5 * p.day_s
    fail_arrivals = steady_trace(seed + 7, fail_s, p.tenants,
                                 interval_s=p.trickle_interval_s)
    fail_arrivals += burst_trace(
        seed + 8, tenants=p.tenants, per_tenant=p.storm_per_tenant,
        at_s=0.2 * fail_s, width_s=p.storm_width_s,
        runtime_s=p.storm_runtime_s)
    fail_arrivals.sort(key=lambda a: a.at_s)

    def enable_standby() -> None:
        from kueue_tpu.resilience.replica import lead
        lead(h.mgr, h.durable, identity="soak-leader", force=True)
        h._want_standby = True
        h.standby = h._make_standby()
    phases.append(SoakPhase(
        "failover-storm", fail_s, fail_arrivals,
        hooks=[(0.0, enable_standby), (0.4 * fail_s, arm_kill)]))

    return phases


def _compiles(h: ScenarioHarness) -> int:
    sv = h._solver
    if sv is None:
        return 0
    return int(getattr(sv, "counters", {}).get("mid_traffic_compiles", 0))


def soak_slo(params: SoakParams, total_arrivals: int) -> SLOSpec:
    """The composed run's gate: queueing bounds scaled to the day
    length plus the four soak gates (ISSUE 18 tentpole verdict)."""
    d = params.day_s
    return SLOSpec(
        min_admitted=total_arrivals,
        class_max_p99_tta_s={"prod": 0.5 * d, "standard": 1.0 * d,
                             "batch": 2.0 * d},
        # outage + readiness evictions give every victim ~one extra
        # admission lap; a healthy backoff keeps laps near one per
        # outage — the ADVERSARY's job is to find the shape that
        # breaks this bound against a weak backoff fixture
        max_requeue_amplification=3.0,
        require_aging_green=True,
        max_journey_burn_rate=1.0,
        max_mid_traffic_compiles_after_warm=0,
        require_zero_live_handouts=True)


def run_soak(params: SoakParams, seed: int = 0,
             scale: str = "custom") -> ScenarioResult:
    """Run the composed schedule; returns a ScenarioResult named
    ``soak`` whose violations ARE the soak verdict. Deterministic per
    (params, seed)."""
    from kueue_tpu.resilience import faultinject

    p = params
    h = ScenarioHarness("soak", seed, tenants=p.tenants,
                        quota_units=p.quota_units, cfg=_soak_cfg(p),
                        cycle_s=p.cycle_s, mk_check=True,
                        remote_clusters=["w1", "w2"], durable=True)
    rng = random.Random(seed ^ 0x50A4)
    state: dict = {"pods_down": False, "compiles_at_warm": None}
    # Pods reach readiness immediately — except while the readiness
    # phase holds them down (then every admission laps through the
    # PodsReady timeout + requeue backoff).
    h.pods_ready_policy = \
        lambda name: None if state["pods_down"] else 0.0

    phases = build_phases(h, p, rng, state)
    total = sum(len(ph.arrivals) for ph in phases)
    slo = soak_slo(p, total)
    # the journey ledger prices its live SLI stream against the same
    # objectives the soak gates on (burn-rate gate is non-vacuous)
    h.set_objectives(slo)

    per_phase = []
    try:
        for ph in phases:
            h.set_phase(ph.name)
            h.run(ph.arrivals, ph.duration_s, hooks=ph.hooks)
            led = getattr(h.mgr, "journey_ledger", None)
            per_phase.append({
                "phase": ph.name,
                "t_end_s": round(h.clock.now() - h.t0, 1),
                "cycles": h.cycles,
                "submitted": h.submitted,
                "admissions": h.admissions,
                "evictions": h._evictions_carry
                + h.mgr.recorder.count_by_reason_prefix("EvictedDueTo"),
                "restarts": h.restarts,
                "promotions": h.promotions,
                "aging": h.mgr.aging_watch.gate(),
                "burn_rates": led.burn_rates() if led is not None else {},
            })
        h.set_phase("drain")
        h.drain(max_cycles=240)
    finally:
        faultinject.uninstall()

    if state["compiles_at_warm"] is None:      # wave shorter than a day
        state["compiles_at_warm"] = 0
    res = h.result(scale, slo)
    res.counters["soak"] = {
        "days": max(3, p.days), "day_s": p.day_s,
        "phases": per_phase,
        "phase_transitions": len(per_phase) + 1,   # + the drain flip
        "quota_edits": state.get("quota_edits", {}).get("n", 0),
        "params": p.to_dict(),
    }
    res.counters["mid_traffic_compiles_after_warm"] = \
        _compiles(h) - state["compiles_at_warm"]
    ret = h.retention_status()
    res.counters["retention"] = ret

    # Teardown: the handout-leak gate needs the manager down first.
    h.mgr.shutdown(checkpoint=False)
    res.counters["live_handouts_at_teardown"] = h.mgr.cache.live_handouts
    res.violations = check_slo(res, slo)

    # Structural checks: the composition must actually have crashed,
    # failed over, and stayed inside every retention cap.
    if h.restarts < 1:
        res.violations.append(
            "composed soak never cold-restarted (crash-storm kill "
            "mis-armed?)")
    if h.promotions < 1:
        res.violations.append(
            "composed soak never promoted a standby (failover-storm "
            "kill mis-armed?)")
    for val_k, cap_k in (("cycle_routes", "cycle_routes_cap"),
                         ("flight_ring", "flight_ring_cap"),
                         ("event_window", "event_window_cap"),
                         ("journeys_retained", "journeys_retained_cap")):
        if ret[cap_k] and ret[val_k] > ret[cap_k]:
            res.violations.append(
                f"harness retention {val_k}={ret[val_k]} exceeds its "
                f"cap {ret[cap_k]} over the composed run")
    return res


def run_soak_scenario(seed: int = 0, scale: str = "full") -> ScenarioResult:
    """Catalog entry (sim/scenarios.py SCENARIOS['soak']): the composed
    multi-day soak at the preset for ``scale``."""
    return run_soak(PRESETS[scale], seed=seed, scale=scale)
