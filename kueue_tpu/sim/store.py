"""In-memory object store with watches — the simulated apiserver.

Semantics mirrored from the kube-apiserver behaviors the reference relies
on (SURVEY.md §5 "communication backend"):
- resourceVersion bumped on every write; stale-RV updates raise Conflict
  (opt-in; server-side-apply style last-writer-wins is the default, since
  the reference does all status writes via SSA — pkg/workload/workload.go:521).
- deletion with finalizers parks the object with deletionTimestamp set;
  it is only removed once the last finalizer is stripped
  (pkg/controller/core/workload_controller.go finalizer GC path).
- watch events (ADDED/MODIFIED/DELETED) are dispatched synchronously to
  registered handlers, carrying the stored objects themselves — the
  client-go informer contract (shared cache pointers, read-only by
  convention; the store never mutates a stored object in place).
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Optional

from kueue_tpu.api.kueue import (clone_cluster_queue, clone_local_queue,
                                 clone_workload)
from kueue_tpu.api.meta import Clock, REAL_CLOCK, new_uid
from kueue_tpu.resilience import faultinject
from kueue_tpu.sim.durable import Fenced  # noqa: F401 — re-exported

# Hand-rolled per-kind deep clones for the hottest objects: semantically
# identical to copy.deepcopy, ~10x faster (reconciler reads + status
# writes copy Workloads hundreds of thousands of times at scale).
_FAST_CLONE = {"Workload": clone_workload,
               "ClusterQueue": clone_cluster_queue,
               "LocalQueue": clone_local_queue}


def _clone(obj):
    fc = _FAST_CLONE.get(type(obj).__name__)
    return fc(obj) if fc is not None else copy.deepcopy(obj)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(ValueError):
    pass


class Invalid(ValueError):
    """Admission-webhook rejection (the apiserver's 422)."""


def kind_of(obj) -> str:
    return type(obj).__name__


def obj_key(obj) -> str:
    meta = obj.metadata
    return f"{meta.namespace}/{meta.name}" if meta.namespace else meta.name


class Store:
    """Keyed by (kind, namespace/name).

    Reads (get/list) and watch events return deep copies. `create`
    returns a copy of the stored object; `update` returns None — the
    written object is owned by the store and callers must re-`get` to
    observe the persisted state."""

    def __init__(self, clock: Clock = REAL_CLOCK, durable=None):
        self._clock = clock
        self._lock = threading.RLock()
        self._objects: dict[str, dict[str, object]] = {}
        self._watchers: dict[str, list[Callable]] = {}
        self._admission_hooks: dict[str, list[Callable]] = {}
        self._rv = 0
        # Optional durability sink (sim/durable.py): every committed
        # mutation appends one WAL record BEFORE its watch event fires,
        # so the log's order is exactly the event order the live
        # controllers consumed — replaying it rebuilds this store
        # bit-for-bit (resilience/recovery.py).
        self._durable = durable
        # Leader fencing (resilience/replica.py + RESILIENCE.md §7):
        # when a FencingToken is attached, every commit validates the
        # token against the durable log's lease BEFORE the WAL append
        # (and the append itself re-checks under the log lock), so a
        # deposed leader's write raises Fenced instead of reaching the
        # log the new leader replays. None = standalone store.
        self.fencing = None

    # -- durability (sim/durable.py + resilience/recovery.py) ---------------

    def attach_durable(self, durable) -> None:
        """Attach a DurableLog mid-life (recovery re-attaches after the
        replay so restored objects are not re-logged; scenario harnesses
        attach before seeding capacity)."""
        self._durable = durable

    def _check_fence(self) -> None:
        """Raise Fenced when this store's leadership epoch is stale.
        Called at the TOP of every mutator — BEFORE the local bucket
        mutates — so a deposed-but-alive leader that survives the
        exception is not left holding phantom objects its own log never
        saw (a retried create must raise Fenced again, not
        AlreadyExists). The checks at _persist and inside
        DurableLog.append remain as backstops."""
        f = self.fencing
        if f is not None:
            f.check()

    def checkpoint_now(self) -> None:
        """Take a full durable checkpoint of the committed state (the
        WAL rotates). No-op without an attached log; a deposed
        leader's checkpoint raises Fenced — it would otherwise replace
        the checkpoint with a stale image and rotate away the new
        leader's live tail."""
        with self._lock:
            if self._durable is not None:
                f = self.fencing
                self._durable.checkpoint(
                    self._objects, self._rv,
                    fence=(f.identity, f.epoch,
                           getattr(f, "name", ""))
                    if f is not None else None)

    def _persist(self, event: str, kind: str, key: str, stored) -> None:
        """The commit point every mutation passes through, just before
        its watch event fires: validate the fencing token (a deposed
        leader raises Fenced here — its write must never reach the log
        the new leader replays, RESILIENCE.md §7), append the WAL
        record, then cross the ``store_write`` crash window
        (RESILIENCE.md §6 — a crash AFTER the append is
        durable-but-unobserved: the write survives restart even though
        no watcher ever saw it), then maybe compact. The crash window
        only exists where a WAL exists, so the injection site is gated
        on an attached log (a fenced standby's own reconcile writes
        must not consume kill points armed for the leader). Caller
        holds the store lock."""
        d = self._durable
        fence = self.fencing
        if fence is not None:
            fence.check()
        if d is None:
            return
        ftup = ((fence.identity, fence.epoch, getattr(fence, "name", ""))
                if fence is not None else None)
        d.append(event, kind, key, stored, t=self._clock.now(),
                 fence=ftup)
        faultinject.site(faultinject.SITE_STORE)
        if d.should_checkpoint():
            d.checkpoint(self._objects, self._rv, fence=ftup)

    def load_object(self, obj) -> object:
        """Recovery-path insert (resilience/recovery.py): place an
        object reconstructed from the durable log into the store
        VERBATIM — uid, resourceVersion and timestamps preserved,
        admission webhooks skipped (they ran before the object was
        first persisted; re-defaulting a restored status would fight
        the durable truth) — and fire the ADDED watch event so the
        derived caches rebuild through the normal event path. Not
        re-logged: the record that produced ``obj`` is already
        durable."""
        kind = kind_of(obj)
        with self._lock:
            key = obj_key(obj)
            bucket = self._objects.setdefault(kind, {})
            if key in bucket:
                raise AlreadyExists(f"{kind} {key} already exists")
            bucket[key] = obj
            self._rv = max(self._rv,
                           obj.metadata.resource_version or 0)
            self._notify(kind, ADDED, obj, None)
            return obj

    def apply_replicated(self, event: str, obj) -> None:
        """Replica-side application of ONE replicated watch record
        (resilience/replica.py: the hot-standby's tail replay, and
        recovery.py's incremental cold restore). Like ``load_object``,
        the object is placed VERBATIM (uid/resourceVersion/timestamps
        preserved, admission webhooks skipped — they ran on the leader
        before the record was persisted) and the ORIGINAL event fires
        so the derived caches advance through the normal watch path —
        the same journal replay the snapshot maintainer already runs.
        Not persisted and not fault-sited: applying a record is
        consumption, not a commit. Event fidelity is defended against
        replay edge cases: an ADDED for a key we already hold becomes
        MODIFIED, a MODIFIED for an unknown key becomes ADDED, and a
        DELETED for an unknown key is a no-op — reconcilers see a
        self-consistent stream even across a bootstrap boundary."""
        kind = kind_of(obj)
        with self._lock:
            key = obj_key(obj)
            bucket = self._objects.setdefault(kind, {})
            old = bucket.get(key)
            self._rv = max(self._rv,
                           obj.metadata.resource_version or 0)
            if event == DELETED:
                if old is None:
                    return
                del bucket[key]
                self._notify(kind, DELETED, obj, old)
                return
            bucket[key] = obj
            if old is None:
                self._notify(kind, ADDED, obj, None)
            else:
                self._notify(kind, MODIFIED, obj, old)

    # -- admission webhooks -------------------------------------------------

    def add_admission_hook(self, kind: str,
                           hook: Callable[[str, object, Optional[object]], None]) -> None:
        """hook(op, obj, old) runs before a create ("CREATE") or update
        ("UPDATE") is persisted — the webhook role. It may mutate obj
        (defaulting) or raise Invalid (validation)."""
        self._admission_hooks.setdefault(kind, []).append(hook)

    def _admit(self, op: str, obj, old) -> None:
        for hook in self._admission_hooks.get(kind_of(obj), []):
            hook(op, obj, old)

    # -- watch registration ------------------------------------------------

    def watch(self, kind: str, handler: Callable[[str, object, Optional[object]], None]) -> None:
        """handler(event_type, obj, old_obj). old_obj is None for ADDED."""
        self._watchers.setdefault(kind, []).append(handler)

    def _notify(self, kind: str, event: str, obj, old) -> None:
        handlers = self._watchers.get(kind, [])
        if not handlers:
            return
        # Handlers receive the stored objects themselves — the client-go
        # informer contract (shared cache pointers, read-only by
        # convention). The store never mutates stored objects in place
        # (writes replace them), so aliasing is safe; copying per event
        # dominated the profile at the 2k-CQ scale.
        for handler in handlers:
            handler(event, obj, old)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj) -> object:
        kind = kind_of(obj)
        with self._lock:
            self._check_fence()
            key = obj_key(obj)
            bucket = self._objects.setdefault(kind, {})
            if key in bucket:
                raise AlreadyExists(f"{kind} {key} already exists")
            stored = _clone(obj)
            self._admit("CREATE", stored, None)
            if not stored.metadata.uid:
                stored.metadata.uid = new_uid(kind.lower())
            if stored.metadata.creation_timestamp is None:
                stored.metadata.creation_timestamp = self._clock.now()
            self._rv += 1
            stored.metadata.resource_version = self._rv
            bucket[key] = stored
            self._persist(ADDED, kind, key, stored)
            self._notify(kind, ADDED, stored, None)
            return _clone(stored)

    def get(self, kind: str, namespace: str, name: str,
            copy_object: bool = True) -> object:
        """copy_object=False returns the stored object itself (the
        informer-lister contract: read-only by convention) — gating
        lookups at the 2k-CQ scale can't afford a deep copy of a
        16-flavor ClusterQueue spec per reconcile."""
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            try:
                stored = self._objects[kind][key]
            except KeyError:
                raise NotFound(f"{kind} {key} not found") from None
            return _clone(stored) if copy_object else stored

    def try_get(self, kind: str, namespace: str, name: str,
                copy_object: bool = True):
        try:
            return self.get(kind, namespace, name, copy_object=copy_object)
        except NotFound:
            return None

    def update(self, obj, expect_rv: Optional[int] = None) -> None:
        """Write back an object. With expect_rv set, raises Conflict on a
        stale resourceVersion (optimistic concurrency); by default the
        write wins (SSA-style — the reference's status writes are all SSA
        and conflict-tolerant). Returns None; re-`get` to observe the
        persisted state."""
        kind = kind_of(obj)
        with self._lock:
            self._check_fence()
            key = obj_key(obj)
            bucket = self._objects.setdefault(kind, {})
            if key not in bucket:
                raise NotFound(f"{kind} {key} not found")
            old = bucket[key]
            if obj is old:
                # In-place mutation of a shared (copy_object=False) read:
                # old == stored would make every such write a silent
                # no-op (no RV bump, no watch event). Fail loudly.
                raise ValueError(
                    f"{kind} {key}: update() with the stored object "
                    "itself (in-place mutation of a shared read?)")
            if expect_rv is not None and old.metadata.resource_version != expect_rv:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {expect_rv} != {old.metadata.resource_version}")
            stored = _clone(obj)
            if self._admission_hooks.get(kind):
                self._admit("UPDATE", stored, _clone(old))
            stored.metadata.uid = old.metadata.uid
            stored.metadata.creation_timestamp = old.metadata.creation_timestamp
            # deletionTimestamp is apiserver-owned: preserve it across writes
            if old.metadata.deletion_timestamp is not None:
                stored.metadata.deletion_timestamp = old.metadata.deletion_timestamp
            # A write that changes nothing does not bump the RV or fire a
            # watch event (apiserver no-op update semantics) — this is what
            # lets status-writing reconcilers settle.
            stored.metadata.resource_version = old.metadata.resource_version
            if stored == old:
                return None
            self._rv += 1
            stored.metadata.resource_version = self._rv
            if stored.metadata.deletion_timestamp is not None and not stored.metadata.finalizers:
                # last finalizer removed -> actually delete
                del bucket[key]
                self._persist(DELETED, kind, key, stored)
                self._notify(kind, DELETED, stored, old)
                return None
            bucket[key] = stored
            self._persist(MODIFIED, kind, key, stored)
            self._notify(kind, MODIFIED, stored, old)
            return None

    def update_status(self, obj, owned_status: bool = False) -> None:
        """Status-subresource write (k8s /status semantics): admission
        webhooks are NOT invoked and only `.status` is persisted — spec
        and metadata changes on obj are ignored. A write that changes
        nothing does not bump the RV or fire a watch event. This is what
        keeps per-admission ClusterQueue/LocalQueue counter refreshes
        from re-validating (and re-copying) a 16-flavor spec at the
        2k-CQ scale."""
        kind = kind_of(obj)
        with self._lock:
            self._check_fence()
            key = obj_key(obj)
            bucket = self._objects.setdefault(kind, {})
            if key not in bucket:
                raise NotFound(f"{kind} {key} not found")
            old = bucket[key]
            if obj is old or obj.status is old.status:
                # A caller holding a shared pointer (copy_object=False
                # read) wrote through it: the no-change check below would
                # compare the status with itself and silently drop the
                # write. Fail loudly instead — build a fresh status
                # (owned_status) or read with a copy.
                raise ValueError(
                    f"{kind} {key}: status aliases the stored object "
                    "(in-place mutation of a shared read?)")
            if obj.status == old.status:
                return None
            stored = copy.copy(old)
            stored.metadata = copy.copy(old.metadata)
            # owned_status: the caller hands over a freshly built status
            # object (reconciler pattern) — no defensive copy needed.
            stored.status = (obj.status if owned_status
                             else copy.deepcopy(obj.status))
            self._rv += 1
            stored.metadata.resource_version = self._rv
            bucket[key] = stored
            self._persist(MODIFIED, kind, key, stored)
            self._notify(kind, MODIFIED, stored, old)
            return None

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            self._check_fence()
            key = f"{namespace}/{name}" if namespace else name
            bucket = self._objects.get(kind, {})
            if key not in bucket:
                raise NotFound(f"{kind} {key} not found")
            old = bucket[key]
            if old.metadata.finalizers:
                if old.metadata.deletion_timestamp is None:
                    stored = _clone(old)
                    stored.metadata.deletion_timestamp = self._clock.now()
                    self._rv += 1
                    stored.metadata.resource_version = self._rv
                    bucket[key] = stored
                    self._persist(MODIFIED, kind, key, stored)
                    self._notify(kind, MODIFIED, stored, old)
                return
            del bucket[key]
            self._persist(DELETED, kind, key, old)
            self._notify(kind, DELETED, old, old)

    def list(self, kind: str, namespace: Optional[str] = None,
             labels: Optional[dict] = None,
             where: Optional[Callable[[object], bool]] = None,
             copy_objects: bool = True) -> list:
        """copy_objects=False returns the stored objects themselves —
        the informer-lister contract (client-go listers return shared
        cache pointers, read-only by convention): callers must not
        mutate. Deep-copying every ClusterQueue per reconcile event is
        what made membership scans quadratic at the 2k-CQ scale."""
        with self._lock:
            out = []
            for obj in self._objects.get(kind, {}).values():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if labels is not None and any(
                        obj.metadata.labels.get(k) != v for k, v in labels.items()):
                    continue
                if where is not None and not where(obj):
                    continue
                out.append(_clone(obj) if copy_objects else obj)
            return out

    def count(self, kind: str) -> int:
        with self._lock:
            return len(self._objects.get(kind, {}))
