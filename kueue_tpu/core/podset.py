"""PodSetInfo: node-selector/toleration/count injection & restore.

Equivalent of the reference's pkg/podset/podset.go:42-176:
- from_assignment: flavor assignment -> nodeLabels/tolerations to inject
- merge: apply the info into a job's pod template (conflict-checked)
- restore: undo the injection on suspend/requeue
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_tpu.api import kueue as api
from kueue_tpu.api.corev1 import PodTemplateSpec, Toleration


class PermanentError(Exception):
    """Unrecoverable merge conflict (reference: podset.go:184 marker)."""


@dataclass
class PodSetInfo:
    name: str = ""
    count: int = 0
    annotations: dict = field(default_factory=dict)
    labels: dict = field(default_factory=dict)
    node_selector: dict = field(default_factory=dict)
    tolerations: list = field(default_factory=list)


def from_assignment(psa: api.PodSetAssignment, resource_flavors: dict,
                    default_count: int) -> PodSetInfo:
    """Build injection info from a PodSetAssignment
    (reference: podset.go:53)."""
    info = PodSetInfo(name=psa.name,
                      count=psa.count if psa.count is not None else default_count)
    seen_flavors = set()
    for flavor_name in psa.flavors.values():
        if flavor_name in seen_flavors:
            continue
        seen_flavors.add(flavor_name)
        flavor = resource_flavors.get(flavor_name)
        if flavor is None:
            raise PermanentError(f"flavor {flavor_name} not found")
        for k, v in flavor.spec.node_labels.items():
            if k in info.node_selector and info.node_selector[k] != v:
                raise PermanentError(f"conflicting node selector for key {k}")
            info.node_selector[k] = v
        info.tolerations.extend(flavor.spec.tolerations)
    return info


def from_update(update: api.PodSetUpdate) -> PodSetInfo:
    return PodSetInfo(name=update.name, labels=dict(update.labels),
                      annotations=dict(update.annotations),
                      node_selector=dict(update.node_selector),
                      tolerations=list(update.tolerations))


def merge(info: PodSetInfo, other: PodSetInfo) -> PodSetInfo:
    """Merge two infos, raising PermanentError on conflicts
    (reference: podset.go:136)."""
    out = PodSetInfo(name=info.name, count=info.count,
                     annotations=dict(info.annotations), labels=dict(info.labels),
                     node_selector=dict(info.node_selector),
                     tolerations=list(info.tolerations))
    for src, dst in ((other.annotations, out.annotations),
                     (other.labels, out.labels),
                     (other.node_selector, out.node_selector)):
        for k, v in src.items():
            if k in dst and dst[k] != v:
                raise PermanentError(f"conflict for key {k}")
            dst[k] = v
    for tol in other.tolerations:
        if tol not in out.tolerations:
            out.tolerations.append(tol)
    return out


def merge_into_template(template: PodTemplateSpec, info: PodSetInfo) -> None:
    """Inject into a pod template (reference: podset.Merge on PodSpec)."""
    for k, v in info.labels.items():
        if template.labels.get(k, v) != v:
            raise PermanentError(f"conflicting label {k}")
        template.labels[k] = v
    for k, v in info.annotations.items():
        if template.annotations.get(k, v) != v:
            raise PermanentError(f"conflicting annotation {k}")
        template.annotations[k] = v
    for k, v in info.node_selector.items():
        if template.spec.node_selector.get(k, v) != v:
            raise PermanentError(f"conflicting node selector {k}")
        template.spec.node_selector[k] = v
    for tol in info.tolerations:
        if tol not in template.spec.tolerations:
            template.spec.tolerations.append(tol)


def restore_template(template: PodTemplateSpec, original: PodSetInfo) -> bool:
    """Reset template to the recorded original (reference: RestorePodSpec).
    Returns True if anything changed."""
    changed = (template.labels != original.labels
               or template.annotations != original.annotations
               or template.spec.node_selector != original.node_selector
               or template.spec.tolerations != original.tolerations)
    template.labels = dict(original.labels)
    template.annotations = dict(original.annotations)
    template.spec.node_selector = dict(original.node_selector)
    template.spec.tolerations = list(original.tolerations)
    return changed


def snapshot_template(name: str, count: int, template: PodTemplateSpec) -> PodSetInfo:
    """Record the pre-injection state for later restore."""
    return PodSetInfo(name=name, count=count,
                      labels=dict(template.labels),
                      annotations=dict(template.annotations),
                      node_selector=dict(template.spec.node_selector),
                      tolerations=list(template.spec.tolerations))
