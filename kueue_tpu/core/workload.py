"""In-memory Workload wrapper (`Info`) plus all status/condition transitions.

Equivalent of the reference's pkg/workload/workload.go:
- Info / PodSetResources (:144-177), NewInfo (:179), ScaledTo (:165)
- FlavorResourceUsage (:209), request totaling (:287-344)
- SetQuotaReservation (:440), SetEvictedCondition (:489)
- Ordering.GetQueueOrderTimestamp (:531-554)
- admission-check state helpers (admissionchecks.go)
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api import kueue as api
from kueue_tpu.api.meta import (
    Condition,
    find_condition,
    is_condition_true,
    set_condition,
)
from kueue_tpu.core.resources import (
    FlavorResource,
    pod_effective_requests,
    scale_requests,
)


def key(wl: api.Workload) -> str:
    return f"{wl.metadata.namespace}/{wl.metadata.name}"


def queue_key(wl: api.Workload) -> str:
    return f"{wl.metadata.namespace}/{wl.spec.queue_name}"


@dataclass(slots=True)
class PodSetResources:
    name: str
    requests: dict  # resource -> total quantity for the whole podset
    count: int
    flavors: dict = field(default_factory=dict)  # resource -> flavor name

    def scaled_to(self, new_count: int) -> "PodSetResources":
        # scale down to per-pod then up, in integer arithmetic, matching
        # the reference's scaleDown/scaleUp (workload.go:336-344)
        per_pod = {k: v // self.count for k, v in self.requests.items()} if self.count else dict(self.requests)
        return PodSetResources(
            name=self.name,
            requests=scale_requests(per_pod, new_count),
            count=new_count,
            flavors=dict(self.flavors),
        )


@dataclass(slots=True)
class AssignmentClusterQueueState:
    """Flavor-fungibility resume state (reference: workload.go /
    flavorassigner LastTriedFlavorIdx)."""

    last_tried_flavor_idx: list = field(default_factory=list)  # per podset: dict[resource -> int]
    cluster_queue_generation: int = 0
    cohort_generation: int = 0

    def next_flavor_to_try(self, ps_idx: int, resource: str) -> int:
        if ps_idx >= len(self.last_tried_flavor_idx):
            return 0
        return self.last_tried_flavor_idx[ps_idx].get(resource, -1) + 1

    def pending_flavors(self) -> bool:
        """True if a next flavor remains to try (reference:
        AssignmentClusterQueueState.PendingFlavors)."""
        for ps in self.last_tried_flavor_idx:
            for idx in ps.values():
                if idx != -1:
                    return True
        return False


def _reclaimable_counts(wl: api.Workload) -> dict:
    return {rp.name: rp.count for rp in wl.status.reclaimable_pods}


def pod_sets_counts_after_reclaim(wl: api.Workload) -> dict:
    reclaim = _reclaimable_counts(wl)
    return {ps.name: ps.count - reclaim.get(ps.name, 0) for ps in wl.spec.pod_sets}


class Info:
    """Pre-processed view of a Workload (reference: workload.Info)."""

    # Class-level defaults so partially-constructed instances
    # (from_assignment, the partial-admission shadow probes) resolve the
    # lazy caches without per-path initialization.
    _key_cache = None
    _arena_slot = -1  # encode-arena slot hint (solver/arena.py)

    def __init__(self, wl: api.Workload, cluster_queue: str = "",
                 excluded_resource_prefixes: Optional[list] = None):
        self.obj = wl
        self.cluster_queue = cluster_queue
        self.last_assignment: Optional[AssignmentClusterQueueState] = None
        self._fru_cache: Optional[dict] = None
        self._fr_keys_cache: Optional[frozenset] = None
        if wl.status.admission is not None:
            self.cluster_queue = wl.status.admission.cluster_queue
            self.total_requests = _total_requests_from_admission(wl)
        else:
            self.total_requests = _total_requests_from_pod_sets(wl)
        if excluded_resource_prefixes:
            for psr in self.total_requests:
                psr.requests = {
                    r: q for r, q in psr.requests.items()
                    if not any(r.startswith(p) for p in excluded_resource_prefixes)
                }

    def update(self, wl: api.Workload) -> None:
        self.obj = wl

    @classmethod
    def from_assignment(cls, wl: api.Workload, cluster_queue: str,
                        assignment) -> "Info":
        """Fast path for assume: the scheduler already computed the
        per-podset requests/flavors (the admission it just wrote came
        from them), so skip re-parsing the admission. The preset usage
        cache also guarantees the cache journal entry equals the solver's
        device-applied usage bit-for-bit."""
        info = cls.__new__(cls)
        info.obj = wl
        info.cluster_queue = cluster_queue
        info.last_assignment = None
        info.total_requests = [
            PodSetResources(
                name=ps.name,
                requests=dict(ps.requests),
                count=ps.count,
                flavors={res: f.name for res, f in (ps.flavors or {}).items()})
            for ps in assignment.pod_sets]
        info._fru_cache = dict(assignment.usage)
        info._fr_keys_cache = None
        return info

    @property
    def key(self) -> str:
        # Memoized: namespace/name are fixed for an Info's lifetime
        # (update() only ever swaps in the same workload's new object),
        # and the f-string build showed up in every per-entry hot loop
        # (arena ensure, preemption scans, requeue bookkeeping).
        k = self._key_cache
        if k is None:
            k = self._key_cache = key(self.obj)
        return k

    def can_be_partially_admitted(self) -> bool:
        return any(ps.count > (ps.min_count if ps.min_count is not None else ps.count)
                   for ps in self.obj.spec.pod_sets)

    def flavor_resource_usage(self) -> dict:
        """FlavorResource -> quantity, memoized: total_requests is fixed
        at Info construction and preemption scans call this per candidate
        per cycle."""
        total = self._fru_cache
        if total is None:
            total = {}
            for psr in self.total_requests:
                for res, q in psr.requests.items():
                    fr = FlavorResource(psr.flavors.get(res, ""), res)
                    total[fr] = total.get(fr, 0) + q
            self._fru_cache = total
        return total

    def flavor_resource_keys(self) -> frozenset:
        """The FlavorResources this workload occupies (memoized)."""
        keys = self._fr_keys_cache
        if keys is None:
            keys = self._fr_keys_cache = frozenset(self.flavor_resource_usage())
        return keys


def _total_requests_from_pod_sets(wl: api.Workload) -> list:
    counts = pod_sets_counts_after_reclaim(wl)
    out = []
    for ps in wl.spec.pod_sets:
        count = counts[ps.name]
        per_pod = pod_effective_requests(ps.template.spec)
        out.append(PodSetResources(name=ps.name, requests=scale_requests(per_pod, count), count=count))
    return out


def _total_requests_from_admission(wl: api.Workload) -> list:
    counts = pod_sets_counts_after_reclaim(wl)
    totals = {ps.name: ps.count for ps in wl.spec.pod_sets}
    out = []
    for psa in wl.status.admission.pod_set_assignments:
        cnt = psa.count if psa.count is not None else totals.get(psa.name, 0)
        psr = PodSetResources(name=psa.name, requests=dict(psa.resource_usage),
                              count=cnt, flavors=dict(psa.flavors))
        if counts.get(psa.name, cnt) != cnt:
            psr = psr.scaled_to(counts[psa.name])
        out.append(psr)
    return out


def mk_request_vector(info: "Info", covers_pods: bool) -> dict:
    """Per-resource totals of an Info's pod sets, with the pods
    resource folded in when the CQ covers it — the ONE request vector
    the MultiKueue capacity-column machinery uses (ISSUE 13): the
    placement scoring (scheduler's flush / the fused solve's encode)
    and the controller's in-flight capacity debit MUST consume the
    same vector, or consecutive cycles would score against capacity
    the debit never consumed."""
    from kueue_tpu.api.corev1 import RESOURCE_PODS
    tot: dict = {}
    for psr in info.total_requests:
        for r, v in psr.requests.items():
            tot[r] = tot.get(r, 0) + v
        if covers_pods:
            tot[RESOURCE_PODS] = tot.get(RESOURCE_PODS, 0) + psr.count
    return tot


# --- status transitions (reference: workload.go:346-623) ---

def is_active(wl: api.Workload) -> bool:
    return wl.spec.active


def has_quota_reservation(wl: api.Workload) -> bool:
    return is_condition_true(wl.status.conditions, api.WORKLOAD_QUOTA_RESERVED)


def is_admitted(wl: api.Workload) -> bool:
    return is_condition_true(wl.status.conditions, api.WORKLOAD_ADMITTED)


def is_finished(wl: api.Workload) -> bool:
    return is_condition_true(wl.status.conditions, api.WORKLOAD_FINISHED)


def is_evicted(wl: api.Workload) -> bool:
    return is_condition_true(wl.status.conditions, api.WORKLOAD_EVICTED)


def is_evicted_by_pods_ready_timeout(wl: api.Workload) -> Optional[Condition]:
    cond = find_condition(wl.status.conditions, api.WORKLOAD_EVICTED)
    if cond and cond.status == "True" and cond.reason == api.EVICTED_BY_PODS_READY_TIMEOUT:
        return cond
    return None


# lifecycle phases (reference: pkg/workload/workload.go Status())
STATUS_PENDING = "pending"
STATUS_QUOTA_RESERVED = "quotaReserved"
STATUS_ADMITTED = "admitted"
STATUS_FINISHED = "finished"


def status(wl: api.Workload) -> str:
    if is_finished(wl):
        return STATUS_FINISHED
    if is_admitted(wl):
        return STATUS_ADMITTED
    if has_quota_reservation(wl):
        return STATUS_QUOTA_RESERVED
    return STATUS_PENDING


def set_quota_reservation(wl: api.Workload, admission: api.Admission, now: float) -> None:
    wl.status.admission = admission
    msg = f"Quota reserved in ClusterQueue {admission.cluster_queue}"
    set_condition(wl.status.conditions, Condition(
        type=api.WORKLOAD_QUOTA_RESERVED, status="True", reason="QuotaReserved",
        message=msg, observed_generation=wl.metadata.generation), now)
    # reset eviction/preemption state (reference: SetQuotaReservation)
    for ctype in (api.WORKLOAD_EVICTED, api.WORKLOAD_PREEMPTED):
        cond = find_condition(wl.status.conditions, ctype)
        if cond and cond.status == "True":
            cond.status = "False"
            cond.reason = "QuotaReserved"
            cond.message = "Previously: " + cond.message
            cond.last_transition_time = now


def unset_quota_reservation_with_condition(wl: api.Workload, reason: str, message: str,
                                           now: float) -> bool:
    """Returns True if anything changed (reference:
    UnsetQuotaReservationWithCondition)."""
    cond = find_condition(wl.status.conditions, api.WORKLOAD_QUOTA_RESERVED)
    changed = wl.status.admission is not None
    wl.status.admission = None
    if cond is None or cond.status != "False" or cond.reason != reason or cond.message != message:
        changed = True
    set_condition(wl.status.conditions, Condition(
        type=api.WORKLOAD_QUOTA_RESERVED, status="False", reason=reason, message=message,
        observed_generation=wl.metadata.generation), now)
    if is_admitted(wl):
        set_condition(wl.status.conditions, Condition(
            type=api.WORKLOAD_ADMITTED, status="False", reason="NoReservation",
            message="The workload has no reservation",
            observed_generation=wl.metadata.generation), now)
        changed = True
    return changed


def pending_patch_needed(wl: api.Workload, reason: str, message: str) -> bool:
    """Pure predicate: would unset_quota_reservation_with_condition change
    anything? Lets the requeue path skip the status clone entirely for
    the (dominant, at scale) already-Pending re-requeue case."""
    if wl.status.admission is not None or is_admitted(wl):
        return True
    cond = find_condition(wl.status.conditions, api.WORKLOAD_QUOTA_RESERVED)
    return (cond is None or cond.status != "False" or cond.reason != reason
            or cond.message != message)


def set_evicted_condition(wl: api.Workload, reason: str, message: str, now: float) -> None:
    set_condition(wl.status.conditions, Condition(
        type=api.WORKLOAD_EVICTED, status="True", reason=reason, message=message,
        observed_generation=wl.metadata.generation), now)


def set_preempted_condition(wl: api.Workload, reason: str, message: str, now: float) -> None:
    set_condition(wl.status.conditions, Condition(
        type=api.WORKLOAD_PREEMPTED, status="True", reason=reason, message=message,
        observed_generation=wl.metadata.generation), now)


def set_deactivation_target(wl: api.Workload, reason: str, message: str, now: float) -> None:
    """reference: workload.SetDeactivationTarget — marks the workload for
    deactivation by its own reconciler (workload_controller.go:528-534)."""
    set_condition(wl.status.conditions, Condition(
        type=api.WORKLOAD_DEACTIVATION_TARGET, status="True", reason=reason,
        message=message, observed_generation=wl.metadata.generation), now)


def set_requeued_condition(wl: api.Workload, reason: str, message: str, status: bool,
                           now: float) -> None:
    set_condition(wl.status.conditions, Condition(
        type=api.WORKLOAD_REQUEUED, status="True" if status else "False",
        reason=reason, message=message,
        observed_generation=wl.metadata.generation), now)


def sync_admitted_condition(wl: api.Workload, now: float) -> bool:
    """Admitted := QuotaReserved AND all admission checks Ready
    (reference: SyncAdmittedCondition)."""
    admitted = has_quota_reservation(wl) and all(
        acs.state == api.CHECK_STATE_READY for acs in wl.status.admission_checks)
    if admitted == is_admitted(wl):
        return False
    if admitted:
        cond = Condition(type=api.WORKLOAD_ADMITTED, status="True", reason="Admitted",
                         message="The workload is admitted",
                         observed_generation=wl.metadata.generation)
    else:
        cond = Condition(type=api.WORKLOAD_ADMITTED, status="False", reason="NoChecks",
                         message="The workload lost its admission checks readiness",
                         observed_generation=wl.metadata.generation)
    set_condition(wl.status.conditions, cond, now)
    return True


# --- admission check state (reference: pkg/workload/admissionchecks.go) ---

def find_admission_check(wl: api.Workload, name: str) -> Optional[api.AdmissionCheckState]:
    for acs in wl.status.admission_checks:
        if acs.name == name:
            return acs
    return None


def set_admission_check_state(states: list, new: api.AdmissionCheckState, now: float) -> None:
    existing = None
    for acs in states:
        if acs.name == new.name:
            existing = acs
            break
    if existing is None:
        new.last_transition_time = now
        states.append(new)
        return
    if existing.state != new.state:
        existing.last_transition_time = now
    existing.state = new.state
    existing.message = new.message
    existing.pod_set_updates = new.pod_set_updates


def sync_admission_check_conditions(wl: api.Workload, check_names: set, now: float) -> bool:
    """Seed Pending states for newly-relevant checks, drop obsolete ones
    (reference: workload_controller.go:354-365 + SyncAdmittedCondition)."""
    changed = False
    existing = {acs.name for acs in wl.status.admission_checks}
    for name in check_names:
        if name not in existing:
            set_admission_check_state(wl.status.admission_checks, api.AdmissionCheckState(
                name=name, state=api.CHECK_STATE_PENDING), now)
            changed = True
    before = len(wl.status.admission_checks)
    wl.status.admission_checks = [a for a in wl.status.admission_checks if a.name in check_names]
    return changed or len(wl.status.admission_checks) != before


def reset_checks_after_eviction(wl: api.Workload, now: float) -> bool:
    """Once an eviction completes (the quota reservation is gone),
    Retry and stale Ready check states return to Pending so the next
    admission re-runs every check (reference:
    workload.ResetChecksOnEviction). Without this a MultiKueue Retry
    after worker-cluster loss would re-trigger check-based eviction the
    moment the workload re-reserves (an evict/requeue livelock), and a
    stale Ready naming the LOST cluster would admit the re-reserved
    workload with no worker actually holding it. Rejected states are
    left alone — they drive deactivation."""
    changed = False
    for acs in list(wl.status.admission_checks):
        if acs.state in (api.CHECK_STATE_RETRY, api.CHECK_STATE_READY):
            set_admission_check_state(
                wl.status.admission_checks,
                api.AdmissionCheckState(
                    name=acs.name, state=api.CHECK_STATE_PENDING,
                    message="Reset to Pending after eviction"), now)
            changed = True
    return changed


def has_all_checks(wl: api.Workload, check_names: set) -> bool:
    existing = {acs.name for acs in wl.status.admission_checks}
    return check_names <= existing


def has_all_checks_ready(wl: api.Workload) -> bool:
    return all(acs.state == api.CHECK_STATE_READY for acs in wl.status.admission_checks)


def has_retry_checks(wl: api.Workload) -> bool:
    return any(acs.state == api.CHECK_STATE_RETRY for acs in wl.status.admission_checks)


def has_rejected_checks(wl: api.Workload) -> bool:
    return any(acs.state == api.CHECK_STATE_REJECTED for acs in wl.status.admission_checks)


def admission_checks_for_workload(wl: api.Workload, cq_checks: dict) -> set:
    """Resolve the set of checks that apply to this workload, honoring
    per-flavor admissionChecksStrategy (reference: workload.go:625).

    cq_checks: dict[check name -> set of flavor names (empty = all flavors)].
    """
    if wl.status.admission is None:
        # Not yet assigned flavors: all checks whose flavor set is unrestricted
        # apply; restricted ones can't be resolved yet.
        return {name for name, flavors in cq_checks.items() if not flavors}
    assigned = set()
    for psa in wl.status.admission.pod_set_assignments:
        assigned.update(psa.flavors.values())
    out = set()
    for name, flavors in cq_checks.items():
        if not flavors or assigned & flavors:
            out.add(name)
    return out


@dataclass
class Ordering:
    """Queue-order timestamp policy (reference: workload.go:531-554).
    pods_ready_requeuing_timestamp: "Eviction" (default) or "Creation"."""

    pods_ready_requeuing_timestamp: str = "Eviction"

    def queue_order_timestamp(self, wl: api.Workload) -> float:
        if self.pods_ready_requeuing_timestamp == "Eviction":
            cond = is_evicted_by_pods_ready_timeout(wl)
            if cond is not None:
                return cond.last_transition_time
        return wl.metadata.creation_timestamp or 0.0


def queued_wait_time(wl: api.Workload, now: float) -> float:
    """Time since last queued: creation, or latest PodsReadyTimeout
    re-queue (reference: workload.QueuedWaitTime)."""
    queued_at = wl.metadata.creation_timestamp or 0.0
    cond = is_evicted_by_pods_ready_timeout(wl)
    if cond is not None:
        queued_at = max(queued_at, cond.last_transition_time)
    return now - queued_at


def deepcopy(wl: api.Workload) -> api.Workload:
    return copy.deepcopy(wl)


def _clone_admission(adm: Optional[api.Admission]) -> Optional[api.Admission]:
    if adm is None:
        return None
    return api.Admission(
        cluster_queue=adm.cluster_queue,
        pod_set_assignments=[
            api.PodSetAssignment(name=a.name, flavors=dict(a.flavors),
                                 resource_usage=dict(a.resource_usage),
                                 count=a.count)
            for a in adm.pod_set_assignments])


def _clone_check_state(c: api.AdmissionCheckState) -> api.AdmissionCheckState:
    return api.AdmissionCheckState(
        name=c.name, state=c.state, message=c.message,
        last_transition_time=c.last_transition_time,
        pod_set_updates=[
            api.PodSetUpdate(name=u.name, labels=dict(u.labels),
                             annotations=dict(u.annotations),
                             node_selector=dict(u.node_selector),
                             tolerations=[copy.copy(t) for t in u.tolerations])
            for u in c.pod_set_updates])


def clone_status(st: api.WorkloadStatus) -> api.WorkloadStatus:
    """Explicit deep clone of WorkloadStatus. Equivalent to copy.deepcopy
    but ~10x faster: every leaf is a flat dataclass of scalars, so the
    generic deepcopy machinery (memo dicts, reduce protocol) is pure
    overhead on the admit hot path."""
    return api.WorkloadStatus(
        conditions=[copy.copy(c) for c in st.conditions],
        admission=_clone_admission(st.admission),
        requeue_state=(copy.copy(st.requeue_state)
                       if st.requeue_state is not None else None),
        reclaimable_pods=[copy.copy(p) for p in st.reclaimable_pods],
        admission_checks=[_clone_check_state(c) for c in st.admission_checks])


def clone_for_status_update(wl: api.Workload) -> api.Workload:
    """Clone for a status-only write: fresh metadata + deep-copied status,
    shared (immutable on this path) spec. The scheduler's admission /
    eviction / pending patches mutate only status; a full deepcopy of the
    pod templates dominated the admit hot path."""
    out = copy.copy(wl)
    out.metadata = copy.copy(wl.metadata)
    out.status = clone_status(wl.status)
    return out
