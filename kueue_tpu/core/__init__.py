"""Core model layer: resource arithmetic, workload Info, podset helpers,
cohort hierarchy, priority resolution, limit ranges.

Mirrors the reference's pkg/resources, pkg/workload, pkg/podset,
pkg/hierarchy, pkg/util/{priority,limitrange}.
"""
