"""Namespace LimitRange summaries: defaulting and validation.

Equivalent of the reference's pkg/util/limitrange: Summarize merges all
LimitRanges in a namespace; ValidatePodSpec checks min/max constraints
(used by the scheduler's nominate step, scheduler.go:542-566).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_tpu.api.corev1 import PodSpec, ResourceList
from kueue_tpu.core.resources import add_requests, max_requests, pod_effective_requests

LIMIT_TYPE_POD = "Pod"
LIMIT_TYPE_CONTAINER = "Container"


@dataclass
class LimitRangeItem:
    type: str = LIMIT_TYPE_CONTAINER
    max: ResourceList = field(default_factory=dict)
    min: ResourceList = field(default_factory=dict)
    default: ResourceList = field(default_factory=dict)
    default_request: ResourceList = field(default_factory=dict)


@dataclass
class LimitRange:
    namespace: str = ""
    name: str = ""
    limits: list = field(default_factory=list)  # list[LimitRangeItem]
    # populated lazily so the object can live in the sim store
    metadata: object = None

    def __post_init__(self):
        if self.metadata is None:
            from kueue_tpu.api.meta import ObjectMeta
            self.metadata = ObjectMeta(name=self.name, namespace=self.namespace)


@dataclass
class Summary:
    """Merged constraints per limit type."""
    items: dict = field(default_factory=dict)  # type -> LimitRangeItem


def summarize(*ranges: LimitRange) -> Summary:
    summary = Summary()
    for lr in ranges:
        for item in lr.limits:
            merged = summary.items.setdefault(item.type, LimitRangeItem(type=item.type))
            # min: keep the largest lower bound; max: keep the smallest upper bound
            for res, v in item.min.items():
                merged.min[res] = max(merged.min.get(res, v), v)
            for res, v in item.max.items():
                merged.max[res] = min(merged.max.get(res, v), v)
            # defaults: first writer wins (matching the reference's merge)
            for res, v in item.default.items():
                merged.default.setdefault(res, v)
            for res, v in item.default_request.items():
                merged.default_request.setdefault(res, v)
    return summary


def apply_defaults(spec: PodSpec, summary: Optional[Summary]) -> None:
    """Default container requests from default_request, then default
    (mutating-webhook behavior)."""
    if summary is None:
        return
    item = summary.items.get(LIMIT_TYPE_CONTAINER)
    if item is None:
        return
    for c in list(spec.containers) + list(spec.init_containers):
        for res, v in item.default_request.items():
            c.requests.setdefault(res, v)
        for res, v in item.default.items():
            c.requests.setdefault(res, v)
            c.limits.setdefault(res, v)


def validate_pod_spec(spec: PodSpec, summary: Summary, path: str = "") -> list:
    """Return human-readable constraint violations
    (reference: limitrange ValidatePodSpec)."""
    reasons = []
    citem = summary.items.get(LIMIT_TYPE_CONTAINER)
    if citem is not None:
        for c in list(spec.containers) + list(spec.init_containers):
            for res, v in c.requests.items():
                if res in citem.min and v < citem.min[res]:
                    reasons.append(f"{path}: container {c.name} requests {res} below LimitRange min")
                if res in citem.max and v > citem.max[res]:
                    reasons.append(f"{path}: container {c.name} requests {res} above LimitRange max")
    pitem = summary.items.get(LIMIT_TYPE_POD)
    if pitem is not None:
        total = pod_effective_requests(spec)
        for res, v in total.items():
            if res in pitem.min and v < pitem.min[res]:
                reasons.append(f"{path}: pod requests {res} below LimitRange min")
            if res in pitem.max and v > pitem.max[res]:
                reasons.append(f"{path}: pod requests {res} above LimitRange max")
    return reasons
