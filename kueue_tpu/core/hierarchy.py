"""Bidirectional ClusterQueue <-> Cohort graph with implicit-cohort lifecycle.

Equivalent of the reference's pkg/hierarchy/manager.go:14-90: cohorts
can exist implicitly (referenced by a CQ but not created as API objects)
and are garbage-collected when the last reference is gone; explicit
cohorts (v1alpha1 Cohort objects) may carry their own quotas and a
parent, forming arbitrary-depth trees.
"""

from __future__ import annotations

from typing import Callable, Generic, Optional, TypeVar

CQ = TypeVar("CQ")
C = TypeVar("C")


class CohortNode(Generic[CQ, C]):
    def __init__(self, name: str, payload: C):
        self.name = name
        self.payload = payload
        self.explicit = False
        self.child_cqs: dict[str, CQ] = {}
        self.child_cohorts: dict[str, "CohortNode[CQ, C]"] = {}
        self.parent: Optional["CohortNode[CQ, C]"] = None

    def has_parent(self) -> bool:
        return self.parent is not None


class Manager(Generic[CQ, C]):
    """Tracks CQ->cohort and cohort->cohort edges.

    cohort_factory builds the payload for a newly-materialized cohort.
    """

    def __init__(self, cohort_factory: Callable[[str], C]):
        self._cohort_factory = cohort_factory
        self.cluster_queues: dict[str, CQ] = {}
        self.cohorts: dict[str, CohortNode[CQ, C]] = {}
        self._cq_cohort: dict[str, str] = {}

    # --- ClusterQueues ---

    def add_cluster_queue(self, name: str, cq: CQ) -> None:
        self.cluster_queues[name] = cq

    def update_cluster_queue_edge(self, name: str, cohort_name: str) -> None:
        """Point CQ at cohort ('' = none), materializing/gc-ing implicit
        cohorts (reference: manager.go:35-78)."""
        old = self._cq_cohort.get(name, "")
        if old == cohort_name:
            return
        if old:
            node = self.cohorts.get(old)
            if node:
                node.child_cqs.pop(name, None)
                self._gc_if_unreferenced(node)
        if cohort_name:
            node = self._get_or_create(cohort_name)
            node.child_cqs[name] = self.cluster_queues[name]
            self._cq_cohort[name] = cohort_name
        else:
            self._cq_cohort.pop(name, None)

    def delete_cluster_queue(self, name: str) -> None:
        self.update_cluster_queue_edge(name, "")
        self.cluster_queues.pop(name, None)

    def cohort_of(self, cq_name: str) -> Optional[CohortNode[CQ, C]]:
        cname = self._cq_cohort.get(cq_name, "")
        return self.cohorts.get(cname) if cname else None

    # --- Cohorts ---

    def add_cohort(self, name: str) -> CohortNode[CQ, C]:
        """Make cohort explicit (API object exists)."""
        node = self._get_or_create(name)
        node.explicit = True
        return node

    def update_cohort_edge(self, name: str, parent_name: str) -> None:
        # Cycle check BEFORE any mutation: a raise must leave the graph
        # untouched (a partial detach would corrupt quota aggregation).
        if parent_name and self._would_cycle(name, parent_name):
            raise ValueError(f"cohort cycle: {name} -> {parent_name}")
        node = self._get_or_create(name)
        if node.parent is not None:
            if node.parent.name == parent_name:
                return
            node.parent.child_cohorts.pop(name, None)
            old_parent = node.parent
            node.parent = None
            self._gc_if_unreferenced(old_parent)
        if parent_name:
            parent = self._get_or_create(parent_name)
            parent.child_cohorts[name] = node
            node.parent = parent

    def delete_cohort(self, name: str) -> None:
        node = self.cohorts.get(name)
        if node is None:
            return
        node.explicit = False
        self.update_cohort_edge(name, "")
        self._gc_if_unreferenced(node)

    def root(self, node: CohortNode[CQ, C]) -> CohortNode[CQ, C]:
        while node.parent is not None:
            node = node.parent
        return node

    def cycle_free(self) -> bool:
        for name in self.cohorts:
            seen = set()
            node = self.cohorts[name]
            while node is not None:
                if node.name in seen:
                    return False
                seen.add(node.name)
                node = node.parent
        return True

    def _would_cycle(self, child: str, parent: str) -> bool:
        node = self.cohorts.get(parent)
        while node is not None:
            if node.name == child:
                return True
            node = node.parent
        return False

    def _get_or_create(self, name: str) -> CohortNode[CQ, C]:
        node = self.cohorts.get(name)
        if node is None:
            node = CohortNode(name, self._cohort_factory(name))
            self.cohorts[name] = node
        return node

    def _gc_if_unreferenced(self, node: CohortNode) -> None:
        if not node.explicit and not node.child_cqs and not node.child_cohorts and node.parent is None:
            self.cohorts.pop(node.name, None)
