"""Workload priority resolution (reference: pkg/util/priority/priority.go).

Priority order of sources: explicit spec.priority (populated by the
webhook/defaulter from WorkloadPriorityClass > pod PriorityClass), else 0.
"""

from __future__ import annotations

from typing import Optional

from kueue_tpu.api import kueue as api


def priority(wl: api.Workload) -> int:
    if wl.spec.priority is not None:
        return wl.spec.priority
    return 0


def priority_from_classes(
    pod_priority_class: str,
    workload_priority_class: str,
    workload_priority_classes: dict,
    priority_classes: dict,
) -> tuple[str, str, int]:
    """Resolve (class_source, class_name, value): WorkloadPriorityClass wins
    over pod PriorityClass (reference: jobframework/reconciler.go:879-962).
    """
    if workload_priority_class:
        wpc: Optional[api.WorkloadPriorityClass] = workload_priority_classes.get(workload_priority_class)
        if wpc is not None:
            return api.WORKLOAD_PRIORITY_CLASS_SOURCE, workload_priority_class, wpc.value
    if pod_priority_class:
        pc: Optional[api.PriorityClass] = priority_classes.get(pod_priority_class)
        if pc is not None:
            return api.POD_PRIORITY_CLASS_SOURCE, pod_priority_class, pc.value
    return "", "", 0
