"""Integer resource arithmetic keyed by (flavor, resource).

Equivalent of the reference's pkg/resources (resource.go:1-30,
requests.go:69): quantities are canonical integers (milli for cpu, raw
scalar otherwise — see kueue_tpu.api.corev1.parse_quantity).
"""

from __future__ import annotations

from typing import Iterable, NamedTuple

from kueue_tpu.api.corev1 import Container, PodSpec, ResourceList


class FlavorResource(NamedTuple):
    flavor: str
    resource: str


# dict[FlavorResource, int]
FlavorResourceQuantities = dict

Requests = dict  # dict[str, int]: resource name -> quantity


def add_requests(a: ResourceList, b: ResourceList) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def max_requests(a: ResourceList, b: ResourceList) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = max(out.get(k, 0), v)
    return out


def scale_requests(r: ResourceList, f: int) -> ResourceList:
    return {k: v * f for k, v in r.items()}


def pod_effective_requests(spec: PodSpec) -> ResourceList:
    """Effective per-pod requests: elementwise
    max(sum of containers, max of init containers) + overhead.

    Equivalent of limitrange.TotalRequests in the reference
    (used at pkg/workload/workload.go:316).
    """
    total: ResourceList = {}
    for c in spec.containers:
        total = add_requests(total, c.requests)
    init_max: ResourceList = {}
    for c in spec.init_containers:
        init_max = max_requests(init_max, c.requests)
    total = max_requests(total, init_max)
    return add_requests(total, spec.overhead)


def container_limits_violations(containers: Iterable[Container]) -> list[str]:
    """Resources whose requests exceed their limits (scheduler validation,
    reference scheduler.go:509-540)."""
    bad = []
    for c in containers:
        for res, req in c.requests.items():
            if res in c.limits and req > c.limits[res]:
                bad.append(res)
    return bad


def add_flavor_quantities(dst: FlavorResourceQuantities, src: FlavorResourceQuantities, sign: int = 1) -> None:
    for fr, q in src.items():
        dst[fr] = dst.get(fr, 0) + sign * q
