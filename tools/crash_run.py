"""Crash-restart chaos driver: seeded kill points end-to-end (ISSUE 10;
hot-standby promotion timing added by ISSUE 15).

Runs the FULL control plane (KueueManager over a durable store:
checkpoint/WAL sim apiserver, controllers, webhooks, scheduler +
pipelined solver) over a fixed arrival schedule several ways:

- an **oracle** run that never crashes,
- a **crash** run killed by an ``InjectedCrash`` at a seeded
  ``(site, hit)`` — any resilience injection site, including the
  ``store_write`` (durable-but-unobserved window) and ``apply_commit``
  (assumed-but-unwritten window) — then restored from the durable
  store (``resilience/recovery.py``) with the SAME solver object
  (exercising ``detach()``) and driven over the remaining schedule,
- a **failover** run killed the same way while a HOT STANDBY
  (``resilience/replica.py``) tails the WAL at one of three lag
  states (``hot``: poll every cycle, ``lagged``: every 3rd, ``cold``:
  never polled until the kill) — the standby PROMOTES (fence + tail
  drain, no cold restore) and drives the remainder.

Verifies the recovery contract (RESILIENCE.md §6/§7) either way:

- **convergence**: the post-recovery admitted set is exactly the
  uncrashed oracle's,
- **no lost admissions**: everything durably admitted before the kill
  stays admitted,
- **no double admissions**: per-CQ cache usage equals the sum of the
  store's admitted workloads (a double admit double-counts usage),
- **no stranded state**: the run settles, the post-shutdown manager
  holds no in-flight cycle and no live snapshot handouts.

Usage:
  python tools/crash_run.py [seed] [site] [hit]        one seeded kill
  python tools/crash_run.py --failover [seed] [site] [hit] [lag]
  python tools/crash_run.py --shard [seed] [site] [hit] [n_shards]
                                              kill ONE admission shard
                                              of a sharded plane
                                              (ISSUE 20) mid-cycle via
                                              its scoped injector; the
                                              survivors keep admitting
                                              and the dead shard is
                                              hot-promoted
  python tools/crash_run.py --sweep [seeds]   every site x seeds, the
                                              cold-restore sweep PLUS
                                              the promotion-timing
                                              sweep (lag state varied
                                              per seed) PLUS the
                                              shard-kill sweep (site x
                                              layout x seed)

Prints one JSON line per run to stderr plus a final verdict line to
stdout; exits non-zero on any divergence. Deterministic for a given
seed (FakeClock + seeded schedules).
"""

import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.core import workload as wlpkg  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402
from kueue_tpu.resilience import faultinject, recovery  # noqa: E402
from kueue_tpu.resilience.faultinject import (  # noqa: E402
    CRASH, FaultInjector, InjectedCrash)
from kueue_tpu.solver import BatchSolver  # noqa: E402

NUM_CQS = 4
WAVES = 5
MAX_CYCLES = 60

# Every site a crash can fire at from the driving thread. compile_warmup
# runs on the governor's background worker — a crash there cannot
# propagate to the driver (a real SIGKILL has no such limit, but the
# in-process simulation does); its kill coverage lives in
# tests/test_recovery.py via the governor's synchronous walk.
CRASH_SITES = (faultinject.SITE_STORE, faultinject.SITE_APPLY,
               faultinject.SITE_DISPATCH, faultinject.SITE_COLLECT,
               faultinject.SITE_SCATTER, faultinject.SITE_REPLAY,
               faultinject.SITE_SPECULATION)

# Follower lag states for the promotion-timing sweep: cycles between
# standby polls (0 = never polled until the promotion itself, so the
# entire tail drains inside promote()).
LAG_MODES = {"hot": 1, "lagged": 3, "cold": 0}

# Shard-kill sites (ISSUE 20): the sites a SHARD's admission cycle
# actually crosses on the cpu route — apply_commit (the assumed-but-
# unwritten tear) and store_write (the shard dies inside the shared
# apiserver's commit, after the WAL append). Device-path sites are the
# solver's; shard schedulers in this harness run solverless, so a kill
# there would be vacuous.
SHARD_CRASH_SITES = (faultinject.SITE_APPLY, faultinject.SITE_STORE)
# "every injection site x N-shard layouts x seeds": both layouts per
# sweep cell.
SHARD_LAYOUTS = (2, 4)


def make_objects():
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(NUM_CQS):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % 2}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=8000)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def make_workload(wave, i, n):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{wave}-{i}", namespace="default", uid=f"wl-{wave}-{i}",
        creation_timestamp=float(n)))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 2000})]))))
    return wl


def make_config():
    cfg = cfgpkg.Configuration()
    cfg.solver.enable = True
    cfg.solver.min_heads = 0
    cfg.solver.routing = "always"
    cfg.store.durable = True
    cfg.store.checkpoint_every = 64
    return cfg


def admitted_keys(mgr):
    return sorted(wlpkg.key(wl) for wl in mgr.store.list("Workload")
                  if wlpkg.has_quota_reservation(wl))


def usage_consistent(mgr):
    """Per-CQ reservation usage in the cache must equal the sum of the
    STORE's admitted workloads — the double-admission detector (a
    workload admitted twice double-counts its usage)."""
    expected: dict = {}
    for wl in mgr.store.list("Workload", copy_objects=False):
        if not wlpkg.has_quota_reservation(wl):
            continue
        info = wlpkg.Info(wl)
        cq = wl.status.admission.cluster_queue
        bucket = expected.setdefault(cq, {})
        for fr, v in info.flavor_resource_usage().items():
            bucket[fr] = bucket.get(fr, 0) + v
    for cq in mgr.cache.hm.cluster_queues:
        reserved, _admitted = mgr.cache.usage_for_cluster_queue(cq)
        want = {fr: v for fr, v in expected.get(cq, {}).items() if v}
        got = {fr: v for fr, v in reserved.items() if v}
        if want != got:
            return False, f"{cq}: store says {want}, cache says {got}"
    return True, ""


def deliver_wave(mgr, wave):
    """Create wave ``wave``'s workloads, skipping any that already
    exist: after a crash the 'client' (the job controllers feeding the
    apiserver) re-submits whatever its in-flight creates lost, exactly
    like a real controller re-reconciling its desired state — and the
    deterministic creation timestamps keep the admission order
    identical to the oracle's."""
    n = wave * NUM_CQS
    for i in range(NUM_CQS):
        if mgr.store.try_get("Workload", "default",
                             f"w{wave}-{i}") is None:
            mgr.store.create(make_workload(wave, i, n + i))


def drive(mgr, clock, next_wave, waves, max_cycles=MAX_CYCLES,
          on_cycle=None):
    """Run cycles, trickling remaining arrival waves; returns (next
    undelivered wave, settled?). Raises InjectedCrash through.
    ``on_cycle`` fires before each cycle (the failover runs poll the
    standby there — its cadence is the swept lag state)."""
    settled = 0
    for cycle in range(max_cycles):
        if on_cycle is not None:
            on_cycle(cycle)
        if next_wave < waves:
            deliver_wave(mgr, next_wave)
            next_wave += 1
            mgr.run_until_idle(max_iterations=1_000_000)
        before = len(admitted_keys(mgr))
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        progressed = len(admitted_keys(mgr)) > before
        busy = (progressed or next_wave < waves
                or mgr.scheduler._inflight is not None)
        settled = 0 if busy else settled + 1
        if settled >= 3:
            return next_wave, True
    return next_wave, False


def run_oracle(seed: int) -> dict:
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=make_config(), clock=clock,
                       solver=BatchSolver())
    for obj in make_objects():
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    _, settled = drive(mgr, clock, 0, WAVES)
    out = {"mode": "oracle", "seed": seed, "settled": settled,
           "admitted": admitted_keys(mgr)}
    mgr.shutdown()
    return out


def run_crash(seed: int, site: str, hit: int) -> dict:
    clock = FakeClock(1000.0)
    solver = BatchSolver()
    mgr = KueueManager(cfg=make_config(), clock=clock, solver=solver)
    for obj in make_objects():
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    durable = mgr.durable

    faultinject.install(FaultInjector({site: {hit: CRASH}}))
    crashed = False
    next_wave = 0
    try:
        next_wave, settled = drive(mgr, clock, 0, WAVES)
    except InjectedCrash:
        crashed = True
    finally:
        faultinject.uninstall()

    pre_admitted = []
    if crashed:
        # The durable store is the ONLY state that survives; the dead
        # manager is discarded un-inspected (its queues/cache/solver
        # bindings are the in-memory state a real SIGKILL loses).
        loaded = durable.load()
        pre_admitted = sorted(
            wlpkg.key(wl)
            for wl in loaded.objects.get("Workload", {}).values()
            if wlpkg.has_quota_reservation(wl))
        mgr = recovery.restore(durable, cfg=make_config(), clock=clock,
                               solver=solver)
        # Re-deliver from the first wave with ANY member missing: the
        # crash may have killed the process mid-wave, losing some of
        # the client's in-flight creates — the client's job is to
        # re-submit them (deliver_wave skips the durable survivors).
        created = {wl.metadata.name
                   for wl in mgr.store.list("Workload",
                                            copy_objects=False)}
        next_wave = 0
        while next_wave < WAVES and all(
                f"w{next_wave}-{i}" in created
                for i in range(NUM_CQS)):
            next_wave += 1
    _, settled = drive(mgr, clock, next_wave, WAVES)

    ok_usage, usage_msg = usage_consistent(mgr)
    out = {
        "mode": "crash", "seed": seed, "site": site, "hit": hit,
        "crashed": crashed, "settled": settled,
        "admitted": admitted_keys(mgr),
        "pre_crash_admitted": pre_admitted,
        "usage_consistent": ok_usage, "usage_msg": usage_msg,
        "recovery": (mgr.last_recovery.to_dict()
                     if mgr.last_recovery is not None else None),
    }
    mgr.shutdown()
    out["inflight_after_shutdown"] = mgr.scheduler._inflight is not None
    out["live_handouts"] = mgr.cache.live_handouts
    return out


def run_failover(seed: int, site: str, hit: int,
                 lag_mode: str = "hot") -> dict:
    """The promotion-timing arm (ISSUE 15): the leader is killed at
    the seeded (site, hit) while a hot standby tails its WAL at the
    given lag state; the standby PROMOTES — fencing epoch bump + tail
    drain, never a cold restore — and drives the remaining schedule.
    The verdict contract is identical to run_crash's."""
    from kueue_tpu.resilience.replica import StandbyReplica, lead

    poll_every = LAG_MODES[lag_mode]
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=make_config(), clock=clock,
                       solver=BatchSolver())
    for obj in make_objects():
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    durable = mgr.durable
    lead(mgr, durable, identity="leader-0")
    standby = StandbyReplica(durable, cfg=make_config(), clock=clock,
                             solver=BatchSolver(), identity="standby-0")

    def on_cycle(cycle):
        if poll_every and cycle % poll_every == 0:
            standby.poll()

    faultinject.install(FaultInjector({site: {hit: CRASH}}))
    crashed = False
    next_wave = 0
    try:
        next_wave, settled = drive(mgr, clock, 0, WAVES,
                                   on_cycle=on_cycle)
    except InjectedCrash:
        crashed = True
    finally:
        faultinject.uninstall()

    pre_admitted = []
    lag_at_kill = None
    if crashed:
        loaded = durable.load()
        pre_admitted = sorted(
            wlpkg.key(wl)
            for wl in loaded.objects.get("Workload", {}).values()
            if wlpkg.has_quota_reservation(wl))
        lag_at_kill = standby.lag_records
        mgr = standby.promote(force=True)
        created = {wl.metadata.name
                   for wl in mgr.store.list("Workload",
                                            copy_objects=False)}
        next_wave = 0
        while next_wave < WAVES and all(
                f"w{next_wave}-{i}" in created
                for i in range(NUM_CQS)):
            next_wave += 1
    _, settled = drive(mgr, clock, next_wave, WAVES)

    ok_usage, usage_msg = usage_consistent(mgr)
    out = {
        "mode": "failover", "seed": seed, "site": site, "hit": hit,
        "lag_mode": lag_mode, "crashed": crashed, "settled": settled,
        "admitted": admitted_keys(mgr),
        "pre_crash_admitted": pre_admitted,
        "usage_consistent": ok_usage, "usage_msg": usage_msg,
        "lag_at_kill": lag_at_kill,
        "promotion": (standby.last_promotion.to_dict()
                      if standby.last_promotion is not None else None),
        "fencing_epoch": durable.fencing_epoch,
    }
    mgr.shutdown()
    out["inflight_after_shutdown"] = mgr.scheduler._inflight is not None
    out["live_handouts"] = mgr.cache.live_handouts
    return out


def drive_shards(scp, clock, next_wave, waves, max_cycles=MAX_CYCLES,
                 promote=True):
    """Round-robin the shards over the arrival schedule, auto-promoting
    any shard found dead at the top of the loop (the harness plays the
    shard supervisor). Returns (next wave, settled?, promotions)."""
    from kueue_tpu.parallel.shards import SHARD_ACTIVE
    settled = 0
    promotions = 0
    admitted_at_death = None
    for _cycle in range(max_cycles):
        if promote:
            for s in list(scp.shards):
                if s.state != SHARD_ACTIVE:
                    if admitted_at_death is None:
                        # What the WAL had durably admitted when the
                        # kill surfaced — the no-lost-admissions
                        # baseline, same arbiter as the restore arm.
                        loaded = scp.durable.load()
                        admitted_at_death = sorted(
                            wlpkg.key(wl) for wl in
                            loaded.objects.get("Workload", {}).values()
                            if wlpkg.has_quota_reservation(wl))
                    scp.promote_shard(s.index)
                    promotions += 1
        if next_wave < waves:
            deliver_wave(scp.plane, next_wave)
            next_wave += 1
            scp.plane.run_until_idle(max_iterations=1_000_000)
        before = len(admitted_keys(scp.plane))
        scp.cycle()
        clock.advance(1.0)
        scp.renew_leases()
        progressed = len(admitted_keys(scp.plane)) > before
        busy = progressed or next_wave < waves
        settled = 0 if busy else settled + 1
        if settled >= 3:
            return next_wave, True, promotions, admitted_at_death
    return next_wave, False, promotions, admitted_at_death


def run_shard(seed: int, site: str, hit: int, n_shards: int = 2) -> dict:
    """The shard-kill/promote arm (ISSUE 20): the seeded (site, hit)
    crash is armed in ONE shard's faultinject scope — co-resident
    shards' cycles never consume it — and fires mid-cycle inside that
    shard; the shared plane survives, the other shards keep admitting
    their cohorts, and the harness hot-promotes the dead shard. The
    verdict contract is run_crash's, against the same single-manager
    oracle: the sharded layout must converge to the identical admitted
    set with zero lost/double/stranded."""
    from kueue_tpu.parallel.shards import ShardedControlPlane

    cfg = cfgpkg.Configuration()
    clock = FakeClock(1000.0)
    scp = ShardedControlPlane(n_shards, cfg=cfg, clock=clock,
                              checkpoint_every=64)
    for obj in make_objects():
        scp.plane.store.create(obj)
    scp.plane.run_until_idle(max_iterations=1_000_000)
    scp.replan()

    victim = seed % n_shards
    faultinject.install(FaultInjector({site: {hit: CRASH}}),
                        scope=f"shard-{victim}")
    try:
        # The crash never propagates: shard_cycle absorbs it and marks
        # the victim killed; drive_shards promotes on the next pass.
        next_wave, settled, promotions, at_death = drive_shards(
            scp, clock, 0, WAVES)
    finally:
        faultinject.uninstall(scope=f"shard-{victim}")
    crashed = promotions > 0
    ok_usage, usage_msg = usage_consistent(scp.plane)
    out = {
        "mode": "shard", "seed": seed, "site": site, "hit": hit,
        "n_shards": n_shards, "victim": victim, "crashed": crashed,
        "settled": settled, "promotions": promotions,
        "admitted": admitted_keys(scp.plane),
        "pre_crash_admitted": at_death or [],
        "usage_consistent": ok_usage, "usage_msg": usage_msg,
        "per_shard_admitted": [s.admitted_total for s in scp.shards],
        "epochs": [s.epoch for s in scp.shards],
    }
    scp.shutdown()
    out["inflight_after_shutdown"] = any(
        s.scheduler._inflight is not None for s in scp.shards)
    out["live_handouts"] = scp.plane.cache.live_handouts
    return out


def verdict(oracle: dict, crash: dict) -> dict:
    lost = sorted(set(crash["pre_crash_admitted"])
                  - set(crash["admitted"]))
    return {
        "converged": crash["admitted"] == oracle["admitted"],
        "lost_admissions": lost,
        "double_admission": not crash["usage_consistent"],
        "stranded": (not crash["settled"]
                     or crash["inflight_after_shutdown"]
                     or crash["live_handouts"] != 0),
        "crashed": crash["crashed"],
    }


def one_run(seed: int, site: str, hit: int,
            lag_mode: str = "", n_shards: int = 0) -> int:
    oracle = run_oracle(seed)
    if n_shards:
        crash = run_shard(seed, site, hit, n_shards)
    elif lag_mode:
        crash = run_failover(seed, site, hit, lag_mode)
    else:
        crash = run_crash(seed, site, hit)
    for r in (oracle, crash):
        print(json.dumps({**r, "admitted": len(r["admitted"])}),
              file=sys.stderr)
    v = verdict(oracle, crash)
    ok = (v["converged"] and not v["lost_admissions"]
          and not v["double_admission"] and not v["stranded"])
    line = {"tool": "crash_run", "mode": crash["mode"], "seed": seed,
            "site": site, "hit": hit, "ok": ok, **v,
            "admitted": len(crash["admitted"])}
    if n_shards:
        line["n_shards"] = n_shards
        line["promotions"] = crash["promotions"]
    elif lag_mode:
        line["lag_mode"] = lag_mode
        line["promotion"] = crash["promotion"]
    print(json.dumps(line))
    return 0 if ok else 1


def sweep(seeds: int) -> int:
    """Every crash site x ``seeds`` seeded kill points, run through
    BOTH recovery paths: the ISSUE-10 cold-restore arm and the
    ISSUE-15 promotion-timing arm (hot standby promoted at a lag state
    varied per seed across hot/lagged/cold). A seeded hit that is
    never reached (the site didn't fire before settle) still must
    converge — it degenerates to a clean run — but each site must fire
    at least once per arm across its seeds or the sweep is vacuous."""
    failures = []
    fired = {(m, s): 0 for m in ("restore", "promote")
             for s in CRASH_SITES}
    oracle_by_seed: dict = {}
    lag_names = sorted(LAG_MODES)
    import zlib
    for site in CRASH_SITES:
        for seed in range(seeds):
            # crc32, not hash(): string hashing is randomized per
            # process, and the sweep must be reproducible
            rng = random.Random(
                (zlib.crc32(site.encode()) & 0xFFFF) * 100_000 + seed)
            # store writes are dense (tens per cycle); device-path
            # sites see a handful of hits per cycle — keep kill points
            # shallow enough to land inside the run for every site
            hit = (rng.randint(5, 120)
                   if site == faultinject.SITE_STORE
                   else rng.randint(0, 8))
            if seed not in oracle_by_seed:
                oracle_by_seed[seed] = run_oracle(seed)
            lag_mode = lag_names[seed % len(lag_names)]
            for mode, run in (("restore",
                               lambda: run_crash(seed, site, hit)),
                              ("promote",
                               lambda: run_failover(seed, site, hit,
                                                    lag_mode))):
                crash = run()
                v = verdict(oracle_by_seed[seed], crash)
                fired[(mode, site)] += 1 if crash["crashed"] else 0
                ok = (v["converged"] and not v["lost_admissions"]
                      and not v["double_admission"]
                      and not v["stranded"])
                line = {"arm": mode, "site": site, "seed": seed,
                        "hit": hit, "ok": ok,
                        **{k: v[k] for k in ("converged", "crashed")}}
                if mode == "promote":
                    line["lag_mode"] = lag_mode
                print(json.dumps(line), file=sys.stderr)
                if not ok:
                    failures.append(line)
    # The shard-kill/promote arm (ISSUE 20): every shard crash site x
    # N-shard layout x seed. The victim shard rotates with the seed;
    # each cell must fire at least once across its seeds or the arm is
    # vacuous.
    for site in SHARD_CRASH_SITES:
        for n_shards in SHARD_LAYOUTS:
            fired[("shard", f"{site}@{n_shards}")] = 0
            for seed in range(seeds):
                rng = random.Random(
                    (zlib.crc32(site.encode()) & 0xFFFF) * 100_000
                    + n_shards * 1000 + seed)
                # A shard's scoped hit counter only advances inside its
                # own cycles, and a 4-shard victim owns a single CQ —
                # keep kill points shallow enough to land for the
                # smallest ownership slice.
                hit = (rng.randint(2, 20)
                       if site == faultinject.SITE_STORE
                       else rng.randint(0, 6))
                if seed not in oracle_by_seed:
                    oracle_by_seed[seed] = run_oracle(seed)
                crash = run_shard(seed, site, hit, n_shards)
                v = verdict(oracle_by_seed[seed], crash)
                fired[("shard", f"{site}@{n_shards}")] += (
                    1 if crash["crashed"] else 0)
                ok = (v["converged"] and not v["lost_admissions"]
                      and not v["double_admission"]
                      and not v["stranded"])
                line = {"arm": "shard", "site": site, "seed": seed,
                        "hit": hit, "n_shards": n_shards, "ok": ok,
                        **{k: v[k] for k in ("converged", "crashed")}}
                print(json.dumps(line), file=sys.stderr)
                if not ok:
                    failures.append(line)
    vacuous = [f"{m}:{s}" for (m, s), n in fired.items() if n == 0]
    ok = not failures and not vacuous
    print(json.dumps({"tool": "crash_run", "mode": "sweep",
                      "seeds": seeds, "sites": len(CRASH_SITES),
                      "arms": ["restore", "promote", "shard"],
                      "shard_layouts": list(SHARD_LAYOUTS),
                      "ok": ok, "failures": failures,
                      "fired": {f"{m}:{s}": n
                                for (m, s), n in fired.items()},
                      "vacuous_sites": vacuous}))
    return 0 if ok else 1


def main():
    argv = sys.argv[1:]
    args = [a for a in argv
            if a not in ("--sweep", "--failover", "--shard")]
    if "--sweep" in argv:
        return sweep(int(args[0]) if args else 20)
    seed = int(args[0]) if args else 1234
    site = args[1] if len(args) > 1 else faultinject.SITE_STORE
    hit = int(args[2]) if len(args) > 2 else 40
    if "--shard" in argv:
        n_shards = int(args[3]) if len(args) > 3 else 2
        return one_run(seed, site, hit, n_shards=n_shards)
    if "--failover" in argv:
        lag = args[3] if len(args) > 3 else "hot"
        return one_run(seed, site, hit, lag_mode=lag)
    return one_run(seed, site, hit)


if __name__ == "__main__":
    sys.exit(main())
