"""Crash-restart chaos driver: seeded kill points end-to-end (ISSUE 10).

Runs the FULL control plane (KueueManager over a durable store:
checkpoint/WAL sim apiserver, controllers, webhooks, scheduler +
pipelined solver) over a fixed arrival schedule three ways:

- an **oracle** run that never crashes,
- a **crash** run killed by an ``InjectedCrash`` at a seeded
  ``(site, hit)`` — any resilience injection site, including the new
  ``store_write`` (durable-but-unobserved window) and ``apply_commit``
  (assumed-but-unwritten window) — then restored from the durable
  store (``resilience/recovery.py``) with the SAME solver object
  (exercising ``detach()``) and driven over the remaining schedule.

Verifies the recovery contract (RESILIENCE.md §6):

- **convergence**: the post-recovery admitted set is exactly the
  uncrashed oracle's,
- **no lost admissions**: everything durably admitted before the kill
  stays admitted,
- **no double admissions**: per-CQ cache usage equals the sum of the
  store's admitted workloads (a double admit double-counts usage),
- **no stranded state**: the run settles, the post-shutdown manager
  holds no in-flight cycle and no live snapshot handouts.

Usage:
  python tools/crash_run.py [seed] [site] [hit]     one seeded kill
  python tools/crash_run.py --sweep [seeds]         every site x seeds

Prints one JSON line per run to stderr plus a final verdict line to
stdout; exits non-zero on any divergence. Deterministic for a given
seed (FakeClock + seeded schedules).
"""

import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.core import workload as wlpkg  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402
from kueue_tpu.resilience import faultinject, recovery  # noqa: E402
from kueue_tpu.resilience.faultinject import (  # noqa: E402
    CRASH, FaultInjector, InjectedCrash)
from kueue_tpu.solver import BatchSolver  # noqa: E402

NUM_CQS = 4
WAVES = 5
MAX_CYCLES = 60

# Every site a crash can fire at from the driving thread. compile_warmup
# runs on the governor's background worker — a crash there cannot
# propagate to the driver (a real SIGKILL has no such limit, but the
# in-process simulation does); its kill coverage lives in
# tests/test_recovery.py via the governor's synchronous walk.
CRASH_SITES = (faultinject.SITE_STORE, faultinject.SITE_APPLY,
               faultinject.SITE_DISPATCH, faultinject.SITE_COLLECT,
               faultinject.SITE_SCATTER, faultinject.SITE_REPLAY,
               faultinject.SITE_SPECULATION)


def make_objects():
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(NUM_CQS):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % 2}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=8000)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def make_workload(wave, i, n):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{wave}-{i}", namespace="default", uid=f"wl-{wave}-{i}",
        creation_timestamp=float(n)))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 2000})]))))
    return wl


def make_config():
    cfg = cfgpkg.Configuration()
    cfg.solver.enable = True
    cfg.solver.min_heads = 0
    cfg.solver.routing = "always"
    cfg.store.durable = True
    cfg.store.checkpoint_every = 64
    return cfg


def admitted_keys(mgr):
    return sorted(wlpkg.key(wl) for wl in mgr.store.list("Workload")
                  if wlpkg.has_quota_reservation(wl))


def usage_consistent(mgr):
    """Per-CQ reservation usage in the cache must equal the sum of the
    STORE's admitted workloads — the double-admission detector (a
    workload admitted twice double-counts its usage)."""
    expected: dict = {}
    for wl in mgr.store.list("Workload", copy_objects=False):
        if not wlpkg.has_quota_reservation(wl):
            continue
        info = wlpkg.Info(wl)
        cq = wl.status.admission.cluster_queue
        bucket = expected.setdefault(cq, {})
        for fr, v in info.flavor_resource_usage().items():
            bucket[fr] = bucket.get(fr, 0) + v
    for cq in mgr.cache.hm.cluster_queues:
        reserved, _admitted = mgr.cache.usage_for_cluster_queue(cq)
        want = {fr: v for fr, v in expected.get(cq, {}).items() if v}
        got = {fr: v for fr, v in reserved.items() if v}
        if want != got:
            return False, f"{cq}: store says {want}, cache says {got}"
    return True, ""


def deliver_wave(mgr, wave):
    """Create wave ``wave``'s workloads, skipping any that already
    exist: after a crash the 'client' (the job controllers feeding the
    apiserver) re-submits whatever its in-flight creates lost, exactly
    like a real controller re-reconciling its desired state — and the
    deterministic creation timestamps keep the admission order
    identical to the oracle's."""
    n = wave * NUM_CQS
    for i in range(NUM_CQS):
        if mgr.store.try_get("Workload", "default",
                             f"w{wave}-{i}") is None:
            mgr.store.create(make_workload(wave, i, n + i))


def drive(mgr, clock, next_wave, waves, max_cycles=MAX_CYCLES):
    """Run cycles, trickling remaining arrival waves; returns (next
    undelivered wave, settled?). Raises InjectedCrash through."""
    settled = 0
    for cycle in range(max_cycles):
        if next_wave < waves:
            deliver_wave(mgr, next_wave)
            next_wave += 1
            mgr.run_until_idle(max_iterations=1_000_000)
        before = len(admitted_keys(mgr))
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        progressed = len(admitted_keys(mgr)) > before
        busy = (progressed or next_wave < waves
                or mgr.scheduler._inflight is not None)
        settled = 0 if busy else settled + 1
        if settled >= 3:
            return next_wave, True
    return next_wave, False


def run_oracle(seed: int) -> dict:
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=make_config(), clock=clock,
                       solver=BatchSolver())
    for obj in make_objects():
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    _, settled = drive(mgr, clock, 0, WAVES)
    out = {"mode": "oracle", "seed": seed, "settled": settled,
           "admitted": admitted_keys(mgr)}
    mgr.shutdown()
    return out


def run_crash(seed: int, site: str, hit: int) -> dict:
    clock = FakeClock(1000.0)
    solver = BatchSolver()
    mgr = KueueManager(cfg=make_config(), clock=clock, solver=solver)
    for obj in make_objects():
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    durable = mgr.durable

    faultinject.install(FaultInjector({site: {hit: CRASH}}))
    crashed = False
    next_wave = 0
    try:
        next_wave, settled = drive(mgr, clock, 0, WAVES)
    except InjectedCrash:
        crashed = True
    finally:
        faultinject.uninstall()

    pre_admitted = []
    if crashed:
        # The durable store is the ONLY state that survives; the dead
        # manager is discarded un-inspected (its queues/cache/solver
        # bindings are the in-memory state a real SIGKILL loses).
        loaded = durable.load()
        pre_admitted = sorted(
            wlpkg.key(wl)
            for wl in loaded.objects.get("Workload", {}).values()
            if wlpkg.has_quota_reservation(wl))
        mgr = recovery.restore(durable, cfg=make_config(), clock=clock,
                               solver=solver)
        # Re-deliver from the first wave with ANY member missing: the
        # crash may have killed the process mid-wave, losing some of
        # the client's in-flight creates — the client's job is to
        # re-submit them (deliver_wave skips the durable survivors).
        created = {wl.metadata.name
                   for wl in mgr.store.list("Workload",
                                            copy_objects=False)}
        next_wave = 0
        while next_wave < WAVES and all(
                f"w{next_wave}-{i}" in created
                for i in range(NUM_CQS)):
            next_wave += 1
    _, settled = drive(mgr, clock, next_wave, WAVES)

    ok_usage, usage_msg = usage_consistent(mgr)
    out = {
        "mode": "crash", "seed": seed, "site": site, "hit": hit,
        "crashed": crashed, "settled": settled,
        "admitted": admitted_keys(mgr),
        "pre_crash_admitted": pre_admitted,
        "usage_consistent": ok_usage, "usage_msg": usage_msg,
        "recovery": (mgr.last_recovery.to_dict()
                     if mgr.last_recovery is not None else None),
    }
    mgr.shutdown()
    out["inflight_after_shutdown"] = mgr.scheduler._inflight is not None
    out["live_handouts"] = mgr.cache.live_handouts
    return out


def verdict(oracle: dict, crash: dict) -> dict:
    lost = sorted(set(crash["pre_crash_admitted"])
                  - set(crash["admitted"]))
    return {
        "converged": crash["admitted"] == oracle["admitted"],
        "lost_admissions": lost,
        "double_admission": not crash["usage_consistent"],
        "stranded": (not crash["settled"]
                     or crash["inflight_after_shutdown"]
                     or crash["live_handouts"] != 0),
        "crashed": crash["crashed"],
    }


def one_run(seed: int, site: str, hit: int) -> int:
    oracle = run_oracle(seed)
    crash = run_crash(seed, site, hit)
    for r in (oracle, crash):
        print(json.dumps({**r, "admitted": len(r["admitted"])}),
              file=sys.stderr)
    v = verdict(oracle, crash)
    ok = (v["converged"] and not v["lost_admissions"]
          and not v["double_admission"] and not v["stranded"])
    print(json.dumps({"tool": "crash_run", "seed": seed, "site": site,
                      "hit": hit, "ok": ok, **v,
                      "admitted": len(crash["admitted"])}))
    return 0 if ok else 1


def sweep(seeds: int) -> int:
    """Every crash site x ``seeds`` seeded kill points. A seeded hit
    that is never reached (the site didn't fire before settle) still
    must converge — it degenerates to a clean run — but each site must
    fire at least once across its seeds or the sweep is vacuous."""
    failures = []
    fired_by_site = {s: 0 for s in CRASH_SITES}
    oracle_by_seed: dict = {}
    import zlib
    for site in CRASH_SITES:
        for seed in range(seeds):
            # crc32, not hash(): string hashing is randomized per
            # process, and the sweep must be reproducible
            rng = random.Random(
                (zlib.crc32(site.encode()) & 0xFFFF) * 100_000 + seed)
            # store writes are dense (tens per cycle); device-path
            # sites see a handful of hits per cycle — keep kill points
            # shallow enough to land inside the run for every site
            hit = (rng.randint(5, 120)
                   if site == faultinject.SITE_STORE
                   else rng.randint(0, 8))
            if seed not in oracle_by_seed:
                oracle_by_seed[seed] = run_oracle(seed)
            crash = run_crash(seed, site, hit)
            v = verdict(oracle_by_seed[seed], crash)
            fired_by_site[site] += 1 if crash["crashed"] else 0
            ok = (v["converged"] and not v["lost_admissions"]
                  and not v["double_admission"] and not v["stranded"])
            line = {"site": site, "seed": seed, "hit": hit, "ok": ok,
                    **{k: v[k] for k in ("converged", "crashed")}}
            print(json.dumps(line), file=sys.stderr)
            if not ok:
                failures.append(line)
    vacuous = [s for s, n in fired_by_site.items() if n == 0]
    ok = not failures and not vacuous
    print(json.dumps({"tool": "crash_run", "mode": "sweep",
                      "seeds": seeds, "sites": len(CRASH_SITES),
                      "ok": ok, "failures": failures,
                      "fired_by_site": fired_by_site,
                      "vacuous_sites": vacuous}))
    return 0 if ok else 1


def main():
    args = [a for a in sys.argv[1:] if a != "--sweep"]
    if "--sweep" in sys.argv[1:]:
        return sweep(int(args[0]) if args else 20)
    seed = int(args[0]) if args else 1234
    site = args[1] if len(args) > 1 else faultinject.SITE_STORE
    hit = int(args[2]) if len(args) > 2 else 40
    return one_run(seed, site, hit)


if __name__ == "__main__":
    sys.exit(main())
