"""Fetch and pretty-print flight-recorder cycle traces and journeys.

Pulls ``/debug/cycles`` from a running VisibilityServer (see
``KueueManager.serve_visibility`` / kueue_tpu/obs/OBSERVABILITY.md) and
renders each cycle as a phase timeline: one header line per cycle
(route, regime, heads, admitted, evictions, faults, breaker state,
duration) followed by its spans as proportional bars, nested sub-spans
(dotted names like ``dispatch.scatter``) indented under their parent.

With ``--journey <workload>`` it instead pulls ``/debug/journeys?wl=``
and renders the workload's end-to-end admission timeline — one line
per journey span (offset since arrival, cycle id, generation token,
route, kind, detail): the "why did this take N cycles" view.

Usage:
    python tools/trace_dump.py http://127.0.0.1:8082 [--slowest K | --n K]
    python tools/trace_dump.py http://127.0.0.1:8082 --journey ns/name
    python tools/trace_dump.py traces.json      # a saved /debug/* body
    some-cmd | python tools/trace_dump.py -     # JSON on stdin
"""

from __future__ import annotations

import argparse
import json
import sys

BAR_WIDTH = 40


def fetch(source: str, slowest: int = 0, n: int = 0,
          journey: str = "") -> dict:
    """Load a /debug/cycles (or /debug/journeys?wl=) payload from a
    base URL, a file, or stdin."""
    if source == "-":
        return json.load(sys.stdin)
    if source.startswith("http://") or source.startswith("https://"):
        import urllib.parse
        import urllib.request
        url = source.rstrip("/")
        if journey:
            if not url.endswith("/debug/journeys"):
                url += "/debug/journeys"
            url += "?wl=" + urllib.parse.quote(journey, safe="")
        else:
            if not url.endswith("/debug/cycles"):
                url += "/debug/cycles"
            qs = []
            if slowest:
                qs.append(f"slowest={slowest}")
            elif n:
                qs.append(f"n={n}")
            if qs:
                url += "?" + "&".join(qs)
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.load(resp)
    with open(source) as f:
        return json.load(f)


def _bar(start_ms: float, dur_ms: float, total_ms: float) -> str:
    if total_ms <= 0:
        return ""
    lo = int(BAR_WIDTH * max(0.0, start_ms) / total_ms)
    hi = int(BAR_WIDTH * min(total_ms, start_ms + dur_ms) / total_ms)
    hi = max(hi, lo + 1)
    return " " * lo + "#" * (hi - lo) + " " * (BAR_WIDTH - hi)


def render(payload: dict, out=None) -> None:
    out = out or sys.stdout
    cycles = payload.get("cycles", [])
    print(f"flight recorder: enabled={payload.get('enabled')} "
          f"capacity={payload.get('capacity')} "
          f"recorded={payload.get('cycles_recorded')} "
          f"showing={len(cycles)} ({payload.get('order', '')})", file=out)
    for c in cycles:
        print(f"\ncycle {c['cycle']}  route={c['route']} "
              f"regime={c['regime']} heads={c['heads']} "
              f"admitted={c['admitted']} evictions={c['evictions']} "
              f"faults={c['faults']} breaker={c['breaker']} "
              f"dur={c['duration_ms']:.1f}ms", file=out)
        total = c["duration_ms"]
        for s in sorted(c["spans"], key=lambda s: s["start_ms"]):
            name = s["name"]
            indent = "  " * name.count(".")
            label = f"{indent}{name}"
            print(f"  {label:<24} |{_bar(s['start_ms'], s['dur_ms'], total)}|"
                  f" {s['dur_ms']:8.2f}ms @ {s['start_ms']:.2f}ms",
                  file=out)
        for a in c.get("annotations", []):
            extra = {k: v for k, v in a.items()
                     if k not in ("kind", "message")}
            print(f"  !! {a['kind']}: {a['message']}"
                  + (f"  {extra}" if extra else ""), file=out)


def render_journey(payload: dict, out=None) -> None:
    """One line per journey span: offset since arrival, cycle id,
    generation token, route, kind, detail fields."""
    out = out or sys.stdout
    j = payload.get("journey", payload)
    t0 = j.get("created_t", 0.0)
    print(f"journey {j['workload']}  cq={j['cluster_queue']} "
          f"class={j['class']} sealed={j['sealed']} "
          f"tta={j['tta_s']}s requeues={j['requeues']} "
          f"admissions={j['admissions']}", file=out)
    for s in j.get("spans", []):
        extra = {k: v for k, v in s.items()
                 if k not in ("kind", "t", "cycle", "generation", "route")}
        print(f"  +{s['t'] - t0:>10.2f}s cycle={s['cycle']:>5} "
              f"gen={s['generation']} "
              f"{(s.get('route') or '-'):<16} {s['kind']:<16} "
              f"{extra if extra else ''}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source",
                    help="base URL of a VisibilityServer, a JSON file "
                         "holding a /debug/cycles body, or - for stdin")
    ap.add_argument("--slowest", type=int, default=0,
                    help="show the K slowest retained cycles")
    ap.add_argument("--n", type=int, default=0,
                    help="show only the last K cycles")
    ap.add_argument("--journey", default="",
                    help="render one workload's journey timeline "
                         "(ns/name or bare name) from /debug/journeys")
    args = ap.parse_args(argv)
    try:
        payload = fetch(args.source, slowest=args.slowest, n=args.n,
                        journey=args.journey)
    except Exception as exc:  # noqa: BLE001 — CLI surface
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if args.journey or "journey" in payload:
        render_journey(payload)
    else:
        render(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
