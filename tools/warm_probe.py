"""Warm probe: operator view of the compile governor (ISSUE 7 tooling).

Stands up the full control plane (KueueManager + BatchSolver) at a
given topology shape, walks the compile governor's shape-bucket ladder
(synchronously, fault-contained — exactly what a production startup's
background thread does), and prints the governor state plus a
per-bucket compile-provenance table:

    fresh      — the bucket's programs really compiled in this process
    cache-hit  — served from the persistent compilation cache
               (solver.compileCacheDir; a primed cache after a restart)
    jit-cache  — already in the in-process jit cache (or no persistent
                 cache configured / supported on this backend)
    skipped    — gave up after max attempts (see the error column)

Point --cache-dir at the production cache root to answer "would a
restart here reuse compiles?": a second invocation with the same dir
and shape should show every bucket cache-hit. The same numbers are
served live at /debug/warmup and in the SIGUSR2 dump (warmup_status is
the single producer — see solver/COMPILE.md).

Usage: python tools/warm_probe.py [--cqs N] [--cohorts N]
           [--pending N] [--cache-dir DIR] [--deadline S] [--json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402
from kueue_tpu.solver import BatchSolver  # noqa: E402


def make_objects(num_cqs: int, num_cohorts: int):
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(num_cqs):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % max(num_cohorts, 1)}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=8000)])]))
        out.append(cq)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cqs", type=int, default=64,
                    help="ClusterQueues in the probed topology")
    ap.add_argument("--cohorts", type=int, default=8)
    ap.add_argument("--pending", type=int, default=None,
                    help="expected pending workloads (pre-sizes the "
                         "encode arena and warms its variants)")
    ap.add_argument("--cache-dir", default="",
                    help="persistent compilation cache root "
                         "(solver.compileCacheDir); the governor stamps "
                         "the per-topology subdirectory itself")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="per-bucket warmup deadline seconds")
    ap.add_argument("--json", action="store_true",
                    help="print the raw status JSON instead of the table")
    args = ap.parse_args()

    cfg = cfgpkg.Configuration()
    cfg.solver.enable = True
    cfg.solver.min_heads = 0
    cfg.solver.compile_cache_dir = args.cache_dir
    cfg.solver.warmup_deadline_s = args.deadline
    mgr = KueueManager(cfg=cfg, clock=FakeClock(1000.0),
                       solver=BatchSolver())
    for obj in make_objects(args.cqs, args.cohorts):
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)

    gov = mgr.warm_governor
    if gov is None:
        print("no warm-capable solver attached", file=sys.stderr)
        return 2
    gov.run_sync(expected_pending=args.pending)
    from kueue_tpu.obs import warmup_status
    st = warmup_status(mgr.scheduler)

    if args.json:
        print(json.dumps(st, indent=1))
    else:
        print(f"governor state : {st['state']}")
        print(f"programs warmed: {st['programs_warmed']}")
        print(f"warmup faults  : {st['warmup_faults']}")
        cache = st["cache_subdir"] or "(no persistent cache)"
        print(f"cache dir      : {cache}")
        print(f"{'width':>7} {'state':>8} {'source':>10} {'programs':>8} "
              f"{'compile_ms':>10} {'attempts':>8}  error")
        for b in st["buckets"]:
            print(f"{b['width']:>7} {b['state']:>8} "
                  f"{str(b['source']):>10} {b['programs']:>8} "
                  f"{b['compile_ms']:>10} {b['attempts']:>8}  "
                  f"{b['error'] or ''}")
    ok = st["state"] in ("warm", "idle")
    print(json.dumps({"tool": "warm_probe", "state": st["state"],
                      "buckets": len(st["buckets"]),
                      "programs_warmed": st["programs_warmed"],
                      "warmup_faults": st["warmup_faults"],
                      "cache_subdir": st["cache_subdir"], "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
