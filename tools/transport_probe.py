"""Transport probe: per-cycle host<->device byte and round-trip table.

Operator tooling for the fully device-resident admission cycle
(ISSUE 11): drives the FULL control plane (KueueManager: sim store,
controllers, scheduler + solver in the production pipelined config)
through a few waves of traffic, then prints one row per recorded
scheduler cycle from the flight recorder's transport fields —

    cycle  route              heads  adm  disp  coll  upload_B  fetch_B

— plus a steady-state summary (device-cycle round-trip counts and
bytes-per-cycle percentiles). The steady-state contract this makes
visible: exactly ONE dispatch and ONE collect per device cycle
(preempt-needing cycles included) and a decision-sized fetch; any
cycle violating it stands out as its own row.

Same CLI contract as tools/chaos_run.py: prints one JSON line per
section to stderr, a final parseable JSON verdict line to stdout, and
exits non-zero when the probe itself detects a transport violation —
a device cycle issuing more than one dispatch, or a lifetime
dispatch/collect imbalance (every dispatch must be collected exactly
once; a single drain trace may legitimately collect several
previously-dispatched cycles at depth 2).

Usage: python tools/transport_probe.py [waves] [cqs] [--json]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.core import workload as wlpkg  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402
from kueue_tpu.solver import BatchSolver  # noqa: E402

DEFAULT_WAVES = 6
DEFAULT_CQS = 8
MAX_CYCLES = 64


def make_objects(num_cqs: int):
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(num_cqs):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % 2}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=8000)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def make_workload(wave: int, i: int, n: int):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{wave}-{i}", namespace="default", uid=f"wl-{wave}-{i}",
        creation_timestamp=float(n)))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 2000})]))))
    return wl


def probe(waves: int = DEFAULT_WAVES, num_cqs: int = DEFAULT_CQS) -> dict:
    cfg = cfgpkg.Configuration()
    cfg.solver.enable = True
    cfg.solver.min_heads = 0
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=cfg, clock=clock, solver=BatchSolver())
    for obj in make_objects(num_cqs):
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    def admitted_count():
        return sum(1 for wl in mgr.store.list("Workload")
                   if wlpkg.has_quota_reservation(wl))

    n = 0
    idle = 0
    for cycle in range(MAX_CYCLES):
        if cycle < waves:
            for i in range(num_cqs):
                mgr.store.create(make_workload(cycle, i, n))
                n += 1
            mgr.run_until_idle(max_iterations=1_000_000)
        before = admitted_count()
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        busy = (cycle < waves
                or mgr.scheduler._inflight is not None
                or admitted_count() > before)
        idle = 0 if busy else idle + 1
        if idle >= 3:
            break

    traces = [t.to_dict() for t in mgr.scheduler.recorder.traces()]
    device = [t for t in traces
              if t["route"].startswith("device") and t["collects"]]
    fetches = sorted(t["fetch_bytes"] / t["collects"] for t in device)
    uploads = sorted(t["upload_bytes"] / max(t["dispatches"], 1)
                     for t in device)

    def pct(sorted_vals, q):
        if not sorted_vals:
            return None
        return sorted_vals[min(len(sorted_vals) - 1,
                               int(q * len(sorted_vals)))]

    # The steady-state contract: at most ONE dispatch per cycle, and
    # every dispatch collected exactly once overall. A single trace may
    # legitimately collect MORE than one previously-dispatched cycle
    # (a depth-2 drain, or a mixed preempt cycle's pre-drain) — those
    # collects belong to earlier dispatches, so the 1:1 balance is a
    # lifetime-counter invariant, not a per-trace one.
    counters = dict(mgr.scheduler.solver.counters)
    violations = [t for t in device if t["dispatches"] > 1]
    balanced = (counters.get("dispatches", 0)
                == counters.get("collects", 0))
    report = {
        "waves": waves,
        "cqs": num_cqs,
        "cycles_recorded": len(traces),
        "device_cycles": len(device),
        "round_trip_violations": [t["cycle"] for t in violations],
        "dispatch_collect_balanced": balanced,
        "fetch_bytes_per_cycle_p50": pct(fetches, 0.5),
        "fetch_bytes_per_cycle_p99": pct(fetches, 0.99),
        "upload_bytes_per_cycle_p50": pct(uploads, 0.5),
        "upload_bytes_per_cycle_p99": pct(uploads, 0.99),
        "lifetime": {k: counters.get(k, 0) for k in (
            "dispatches", "collects", "upload_bytes", "fetch_bytes",
            "establishes", "mid_traffic_compiles")},
        "traces": traces,
    }
    mgr.scheduler.stop()
    return report


def render_table(report: dict) -> str:
    head = (f"{'cycle':>6} {'route':<22} {'heads':>5} {'adm':>4} "
            f"{'disp':>4} {'coll':>4} {'upload_B':>9} {'fetch_B':>8}")
    lines = [head, "-" * len(head)]
    for t in report["traces"]:
        lines.append(
            f"{t['cycle']:>6} {t['route']:<22} {t['heads']:>5} "
            f"{t['admitted'] if t['admitted'] is not None else '-':>4} "
            f"{t['dispatches']:>4} {t['collects']:>4} "
            f"{t['upload_bytes']:>9} {t['fetch_bytes']:>8}")
    lines.append("-" * len(head))
    lines.append(
        f"device cycles: {report['device_cycles']}  "
        f"fetch/cycle p50: {report['fetch_bytes_per_cycle_p50']}  "
        f"upload/cycle p50: {report['upload_bytes_per_cycle_p50']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    waves = int(argv[0]) if len(argv) > 0 else DEFAULT_WAVES
    num_cqs = int(argv[1]) if len(argv) > 1 else DEFAULT_CQS
    report = probe(waves, num_cqs)
    if as_json:
        print(json.dumps(report), file=sys.stderr, flush=True)
    else:
        print(render_table(report), file=sys.stderr, flush=True)
    verdict = {k: v for k, v in report.items() if k != "traces"}
    verdict["ok"] = (not report["round_trip_violations"]
                     and report["dispatch_collect_balanced"])
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
