"""Soak driver: the composed multi-day schedule + the adversarial
hunt end-to-end (ISSUE 18 tooling; see kueue_tpu/sim/SCENARIOS.md and
RESILIENCE.md §8).

Default mode runs the composed virtual-time soak (sim/soak.py) at a
preset scale through the FULL control plane — diurnal waves -> quota
churn -> cluster loss -> readiness storm -> crash -> mid-storm
failover on ONE manager/DurableLog/FakeClock — and evaluates the soak
gate: AgingWatch green at run end, zero mid-traffic compiles after
virtual day 1, bounded journey burn rate, zero live snapshot handouts
at teardown, plus the queueing SLOs and the harness retention caps.

``--hunt N`` runs the adversarial search instead (sim/adversary.py):
N seeded mutants of the schedule, first interesting failure shrunk to
its minimal perturbation and emitted as a replayable scenario spec
(``--json DIR`` writes it as ``soak_repro_s<seed>.json``). The hunt
exits non-zero when it FOUND a violation — red means the config under
test broke, which is what CI must surface. ``--weak`` plants the
undersized-backoff fixture (the acceptance weakness) under the hunt.

``--replay SPEC.json`` replays a repro spec standalone and gates it
like a normal soak run — the repro corpus workflow.

``--shapes`` prints the warm-ladder feed: adversarially-synthesized
preempt-storm geometries bucketed to (B, rank) keys, with the keys the
current preempt_shape_ladder would NOT precompile (no soak runs; pure
shape arithmetic).

Deterministic for a (params, seed) pair: virtual time only, seeded
traces, seeded kill points, seeded mutation draws. Prints one JSON
line per run to stderr plus a final verdict line on stdout
(chaos_run.py's contract); exits non-zero on red.

Usage:
  python tools/soak_run.py [--seed N] [--scale smoke|full] [--json DIR]
                           [--hunt BUDGET] [--weak] [--shapes]
                           [--samples N] [--replay SPEC.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu.sim import adversary  # noqa: E402
from kueue_tpu.sim.soak import PRESETS, run_soak  # noqa: E402


def _verdict(res, seed: int, scale: str) -> dict:
    soak = res.counters.get("soak", {})
    return {
        "tool": "soak_run", "seed": seed, "scale": scale, "ok": res.ok,
        "days": soak.get("days"), "cycles": res.cycles,
        "phase_transitions": soak.get("phase_transitions"),
        "submitted": res.submitted, "admitted": res.admitted,
        "restarts": res.restarts, "promotions": res.promotions,
        "aging_ok": res.counters.get("aging", {}).get("ok"),
        "violations": list(res.violations),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Composed virtual-time soak + adversarial traffic hunt")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scale", choices=sorted(PRESETS), default="smoke")
    ap.add_argument("--json", metavar="DIR", default=None,
                    help="write result/repro JSON artifacts")
    ap.add_argument("--hunt", type=int, metavar="BUDGET", default=None,
                    help="adversarial search with BUDGET mutant probes")
    ap.add_argument("--weak", action="store_true",
                    help="plant the weak-backoff fixture under the hunt "
                         "(the acceptance weakness)")
    ap.add_argument("--shapes", action="store_true",
                    help="print the preempt-storm (B, rank) ladder feed "
                         "and exit")
    ap.add_argument("--samples", type=int, default=64,
                    help="--shapes: geometries to synthesize")
    ap.add_argument("--replay", metavar="SPEC.json", default=None,
                    help="replay a shrunk repro spec standalone")
    args = ap.parse_args(argv)
    if args.json:
        os.makedirs(args.json, exist_ok=True)

    base = PRESETS[args.scale]
    if args.weak:
        base = adversary.weak_backoff_fixture(base)

    if args.shapes:
        print(json.dumps(adversary.preempt_shape_report(
            base, seed=args.seed, samples=args.samples), indent=2))
        return 0

    if args.replay:
        with open(args.replay) as f:
            spec = json.load(f)
        name, seed, params = adversary.from_spec(spec)
        res = run_soak(params, seed=seed, scale=name)
        print(json.dumps(res.to_dict()), file=sys.stderr)
        print(json.dumps(_verdict(res, seed, name)))
        return 0 if res.ok else 1

    if args.hunt is not None:
        rep = adversary.search(base, seed=args.seed, budget=args.hunt,
                               scale=args.scale)
        for probe in rep["probes"]:
            print(json.dumps(probe), file=sys.stderr)
        found = bool(rep["findings"])
        if rep["repro"] and args.json:
            path = os.path.join(args.json,
                                rep["repro"]["scenario"] + ".json")
            with open(path, "w") as f:
                json.dump(rep["repro"], f, indent=2, sort_keys=True)
        print(json.dumps({
            "tool": "soak_run", "mode": "hunt", "seed": args.seed,
            "scale": args.scale, "weak": args.weak,
            "budget": args.hunt, "evals": rep["evals"],
            # red == the hunt FOUND a gate violation
            "ok": not found, "findings": len(rep["findings"]),
            "shrink": rep["shrink"], "repro": rep["repro"],
        }))
        return 1 if found else 0

    res = run_soak(base, seed=args.seed, scale=args.scale)
    print(json.dumps(res.to_dict()), file=sys.stderr)
    if args.json:
        with open(os.path.join(args.json, "soak.json"), "w") as f:
            json.dump(res.to_dict(), f, indent=2, sort_keys=True)
    print(json.dumps(_verdict(res, args.seed, args.scale)))
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
