"""Chaos driver: a seeded fault schedule end-to-end (ISSUE 3 tooling).

Runs the FULL control plane (KueueManager: sim store, controllers,
webhooks, scheduler + solver) twice over an identical arrival schedule
— once clean, once with a seeded fault schedule installed at every
resilience injection site (dispatch raise, collect hang/corruption,
arena-scatter corruption, journal-replay faults) for the first
`inject_cycles` admission cycles — then verifies the chaos run

- never deadlocked (both runs settle within a bounded cycle count),
- converged to the clean run's exact admitted workload set, and
- surfaced its outage timeline as Scheduler system events.

Prints one JSON line per run plus a final verdict line; exits non-zero
on divergence. Deterministic for a given seed (FakeClock + seeded
schedule + seeded breaker jitter).

`--storm` runs the overload variant instead (ISSUE 5): the same full
control plane under a workload storm with a deliberately-blown cycle
budget — the degradation ladder must engage (shed/survival cycles,
heads requeued), keep admitting throughout, recover to normal once the
budget is realistic again, and converge to the no-ladder run's exact
admitted set.

Usage: python tools/chaos_run.py [seed] [inject_cycles] [--storm]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.core import workload as wlpkg  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402
from kueue_tpu.resilience import faultinject  # noqa: E402
from kueue_tpu.resilience.faultinject import FaultInjector  # noqa: E402
from kueue_tpu.solver import BatchSolver  # noqa: E402

NUM_CQS = 6
WAVES = 5
MAX_CYCLES = 120


def make_objects():
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(NUM_CQS):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % 2}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=8000)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def make_workload(wave, i, n):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{wave}-{i}", namespace="default", uid=f"wl-{wave}-{i}",
        creation_timestamp=float(n)))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 2000})]))))
    return wl


def admitted_keys(mgr):
    return sorted(wlpkg.key(wl) for wl in mgr.store.list("Workload")
                  if wlpkg.has_quota_reservation(wl))


def run(seed: int, inject_cycles: int, chaotic: bool) -> dict:
    cfg = cfgpkg.Configuration()
    cfg.solver.enable = True
    cfg.solver.min_heads = 0
    cfg.solver.watchdog_safety_factor = 2.0
    cfg.solver.watchdog_min_deadline_s = 0.1
    # Cold cycles legitimately carry a jit compile: the no-estimate
    # deadline must clear it, while warm deadlines (estimate x factor)
    # drop to ~0.1s so the injected 0.2s hangs reliably trip.
    cfg.solver.watchdog_max_deadline_s = 2.0
    cfg.solver.breaker_fault_threshold = 2
    cfg.solver.breaker_backoff_base_s = 2.0
    cfg.solver.breaker_backoff_max_s = 8.0
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=cfg, clock=clock, solver=BatchSolver())
    mgr.scheduler.breaker._rng.seed(seed)  # deterministic jitter
    for obj in make_objects():
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)

    injector = (FaultInjector.scripted(seed, horizon=64, delay_s=0.2)
                if chaotic else None)
    if injector is not None:
        faultinject.install(injector)
    n = 0
    settled = 0
    cycles = 0
    deadlocked = True
    try:
        for cycle in range(MAX_CYCLES):
            if injector is not None and cycle == inject_cycles:
                faultinject.uninstall()
            if cycle < WAVES:  # trickled arrivals keep the arena churning
                for i in range(NUM_CQS):
                    mgr.store.create(make_workload(cycle, i, n))
                    n += 1
                mgr.run_until_idle(max_iterations=1_000_000)
            before = len(admitted_keys(mgr))
            mgr.scheduler.schedule(timeout=0)
            mgr.run_until_idle(max_iterations=1_000_000)
            clock.advance(1.0)
            cycles = cycle + 1
            progressed = len(admitted_keys(mgr)) > before
            injecting = injector is not None and cycle < inject_cycles
            busy = (progressed or injecting
                    or mgr.scheduler._inflight is not None)
            settled = 0 if busy else settled + 1
            if settled >= 3:
                deadlocked = False
                break
    finally:
        faultinject.uninstall()

    s = mgr.scheduler
    return {
        "mode": "chaos" if chaotic else "clean",
        "seed": seed,
        "cycles": cycles,
        "deadlocked": deadlocked,
        "admitted": admitted_keys(mgr),
        "solver_faults": s.solver_faults,
        "fired": dict(injector.fired) if injector else {},
        "breaker": {"state": s.breaker.state, "trips": s.breaker.trips,
                    "recoveries": s.breaker.recoveries,
                    "last_recovery_cycles": s.breaker.last_recovery_cycles},
        "cycle_counts": dict(s.cycle_counts),
        "dispatch_timeouts": s.solver.counters["dispatch_timeouts"],
        "events": [f"{e.type}/{e.reason}: {e.message}"
                   for e in mgr.recorder.events if e.kind == "Scheduler"],
    }


def run_storm(seed: int, laddered: bool) -> dict:
    """One overload-storm run through the full KueueManager: a big
    burst of arrivals with (optionally) a cycle budget every storm
    cycle blows, relaxed once the storm subsides."""
    from kueue_tpu.resilience.degrade import NORMAL, DegradationLadder
    cfg = cfgpkg.Configuration()
    cfg.solver.enable = True
    cfg.solver.min_heads = 0
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=cfg, clock=clock, solver=BatchSolver())
    s = mgr.scheduler
    if laddered:
        # Forced-overload budget: every real cycle blows 1ns, so the
        # ladder's walk is deterministic regardless of machine speed;
        # relaxed to 60s at the subside point below.
        s.ladder = DegradationLadder(budget_s=1e-9, shed_heads=3,
                                     survival_heads=1, escalate_after=1,
                                     recovery_cycles=2, ewma_alpha=1.0)
    for obj in make_objects():
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    n = 0
    for wave in range(6):  # the storm: 36 workloads at once
        for i in range(NUM_CQS):
            mgr.store.create(make_workload(wave, i, n))
            n += 1
    mgr.run_until_idle(max_iterations=1_000_000)
    for cycle in range(40):
        if 12 <= cycle < 25:
            # identical post-storm trickle in both runs: keeps heads
            # flowing so the ladder keeps observing and recovers
            for i in range(NUM_CQS):
                mgr.store.create(make_workload(6 + cycle, i, n))
                n += 1
            mgr.run_until_idle(max_iterations=1_000_000)
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        if laddered and cycle == 12:
            s.ladder.budget_s = 60.0  # the storm subsided
    lad = s.ladder
    return {
        "mode": "storm-laddered" if laddered else "storm-clean",
        "seed": seed,
        "admitted": admitted_keys(mgr),
        "state": lad.state,
        "recovered": lad.state == NORMAL,
        "escalations": lad.escalations,
        "recoveries": lad.recoveries,
        "cycles_shed": lad.cycles_shed,
        "shed_heads_requeued": s.shed_heads_requeued,
        "survival_cycles": s.cycle_counts.get("cpu-survival", 0),
        "cycle_counts": dict(s.cycle_counts),
        "events": [f"{e.type}/{e.reason}: {e.message}"
                   for e in mgr.recorder.events
                   if e.kind == "Scheduler" and "Degraded" in e.reason],
    }


def main_storm(seed: int) -> int:
    clean = run_storm(seed, laddered=False)
    storm = run_storm(seed, laddered=True)
    for r in (clean, storm):
        print(json.dumps({**r, "admitted": len(r["admitted"]),
                          "events": r["events"][:8]}), file=sys.stderr)
    ok = (storm["escalations"] >= 1 and storm["cycles_shed"] >= 1
          and storm["shed_heads_requeued"] >= 1
          and storm["survival_cycles"] >= 1 and storm["recovered"]
          and storm["admitted"] == clean["admitted"])
    print(json.dumps({
        "tool": "chaos_run", "mode": "storm", "seed": seed, "ok": ok,
        "admitted": len(storm["admitted"]),
        "escalations": storm["escalations"],
        "recoveries": storm["recoveries"],
        "cycles_shed": storm["cycles_shed"],
        "shed_heads_requeued": storm["shed_heads_requeued"],
        "survival_cycles": storm["survival_cycles"],
        "recovered": storm["recovered"],
    }))
    return 0 if ok else 1


def main():
    args = [a for a in sys.argv[1:] if a != "--storm"]
    storm = "--storm" in sys.argv[1:]
    seed = int(args[0]) if args else 1234
    if storm:
        return main_storm(seed)
    inject_cycles = int(args[1]) if len(args) > 1 else 12
    clean = run(seed, inject_cycles, chaotic=False)
    chaos = run(seed, inject_cycles, chaotic=True)
    for r in (clean, chaos):
        print(json.dumps({**r, "admitted": len(r["admitted"]),
                          "events": r["events"][:8]}), file=sys.stderr)
    ok = (not clean["deadlocked"] and not chaos["deadlocked"]
          and clean["admitted"] == chaos["admitted"])
    print(json.dumps({
        "tool": "chaos_run", "seed": seed, "ok": ok,
        "admitted": len(chaos["admitted"]),
        "faults_fired": sum(chaos["fired"].values()),
        "solver_faults": chaos["solver_faults"],
        "breaker_trips": chaos["breaker"]["trips"],
        "recovery_cycles": chaos["breaker"]["last_recovery_cycles"],
        "chaos_cycles": chaos["cycles"], "clean_cycles": clean["cycles"],
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
