"""Scenario driver: production-realism traffic suites end-to-end
(ISSUE 8 tooling; see kueue_tpu/sim/SCENARIOS.md for the catalog).

Runs one or more sim scenarios (sim/scenarios.py) through the FULL
control plane (KueueManager: sim store, webhooks, controllers,
scheduler) on the virtual clock and evaluates each against its SLOSpec
gates (perf/checker.py): per-priority-class p99 time-to-admission,
degradation-ladder recovery, requeue amplification, zero starvation,
plus the scenario's own invariants (jitter de-sync, no double
dispatch, orphan GC, job-integration parity).

Deterministic for a (seed, scale) pair: virtual time only, seeded
traces, seeded backoff jitter. A CI failure replays from the seed in
the verdict line alone.

Prints one JSON line per scenario to stderr plus a final verdict line
on stdout (chaos_run.py's contract); exits non-zero if any gate is
red. `--json DIR` additionally writes one `<scenario>.json` artifact
per run.

Usage:
  python tools/scenario_run.py [scenario ...] [--seed N]
                               [--scale smoke|full] [--json DIR]
                               [--list]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu.sim.scenarios import (  # noqa: E402
    list_scenarios, run_scenario)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Run production-realism sim scenarios with SLO gates")
    ap.add_argument("scenarios", nargs="*",
                    help="scenario names (default: the full catalog)")
    ap.add_argument("--list", action="store_true",
                    help="print the scenario catalog and exit")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scale", choices=("smoke", "full"), default="smoke")
    ap.add_argument("--json", metavar="DIR", default=None,
                    help="write one <scenario>.json artifact per run")
    ap.add_argument("--solver", action="store_true",
                    help="run with the production batched solver and its "
                         "route-coverage gate (solver-gated scenarios "
                         "only, e.g. tenant_storm)")
    args = ap.parse_args(argv)

    if args.list:
        for name in list_scenarios():
            print(name)
        return 0

    names = args.scenarios or list_scenarios()
    unknown = [n for n in names if n not in list_scenarios()]
    if unknown:
        ap.error(f"unknown scenario(s) {', '.join(unknown)}; "
                 f"catalog: {', '.join(list_scenarios())}")
    if args.json:
        os.makedirs(args.json, exist_ok=True)

    results = []
    for name in names:
        res = run_scenario(name, seed=args.seed, scale=args.scale,
                           solver=args.solver)
        results.append(res)
        print(json.dumps(res.to_dict()), file=sys.stderr)
        if args.json:
            path = os.path.join(args.json, f"{name}.json")
            with open(path, "w") as f:
                json.dump(res.to_dict(), f, indent=2, sort_keys=True)

    ok = all(r.ok for r in results)
    print(json.dumps({
        "tool": "scenario_run", "seed": args.seed, "scale": args.scale,
        "scenarios": len(results), "ok": ok,
        "red": sorted(r.name for r in results if not r.ok),
        "violations": [v for r in results for v in r.violations],
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
