"""Mesh probe: multi-host domain layout, balance and DCN traffic table.

Operator tooling for the multi-host DCN scale-out (ISSUE 13): forces a
host-platform device count (simulated hosts), builds the two-axis
``("hosts", "cohorts")`` mesh for each requested host count, runs the
sharded admission cycle on synthetic north-star-shaped traffic, and
reports —

- per-host conflict-domain assignment (the planner's cost-balanced
  layout vs the naive round-robin baseline),
- the imbalance ratio (max/mean device load; FAILS the probe > 1.5x),
- DCN-collective bytes per cycle (Phase A all_gather vs the Phase B
  reduction tensors — the layout contract that only the small
  per-domain reductions cross hosts in Phase B),
- the weak-scaling curve: per-cycle wall time with conflict domains
  per device held constant across host counts (sub-linear growth in
  total domains is the scale-out win),
- decision bit-identity of every mesh shape against the single-chip
  fused oracle (--check-identity: randomized seeds, exit non-zero on
  any divergence).

Same CLI contract as tools/chaos_run.py: human table (or --json) to
stderr, one parseable JSON verdict line to stdout, non-zero exit on a
violated gate (imbalance > 1.5x, or identity divergence under
--check-identity). The weak-scaling curve is REPORTED but never gated
here: sub-linearity is only judgeable on real multi-host devices
(simulated hosts share one machine's cores), so the judging — or the
refusal into the device-witness-debt manifest — lives in
bench.bench_multihost.

Usage: python tools/mesh_probe.py [--hosts 1,2,4,8] [--devices 8]
           [--cqs-per-host 64] [--wl-per-host 128] [--cycles 4]
           [--check-identity] [--seed 0] [--json]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _force_devices(n: int) -> None:
    """Must run before jax import: the host-platform device count is
    latched at backend init (the simulate-multi-host knob the ISSUE
    names: XLA_FLAGS=--xla_force_host_platform_device_count)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            f"{flags} --xla_force_host_platform_device_count={n}".strip()


IMBALANCE_GATE = 1.5


def _build_inputs(num_cqs: int, num_cohorts: int, num_workloads: int,
                  seed: int = 0):
    import jax.numpy as jnp
    import numpy as np

    from kueue_tpu.solver.encode import State
    from kueue_tpu.solver.synth import synth_solver_inputs
    topo, usage, cohort_usage, wl = synth_solver_inputs(
        num_cqs=num_cqs, num_cohorts=num_cohorts, num_flavors=4,
        num_resources=2, num_workloads=num_workloads, seed=seed)
    topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}

    class Batch:
        requests = wl["requests"]
        podset_active = wl["podset_active"]
        wl_cq = wl["wl_cq"]
        priority = wl["priority"]
        timestamp = wl["timestamp"]
        eligible = wl["eligible"]
        solvable = wl["solvable"]

    state = State(usage=usage, cohort_usage=cohort_usage)
    return topo, topo_dev, state, Batch, wl, np


def _dcn_bytes(mesh, W, P, R, F, Q, C) -> dict:
    """Cross-host collective bytes per cycle for a (hosts, per_host)
    mesh: each host ships (H-1)/H of a gathered/reduced tensor across
    DCN. Phase A gathers the per-workload assignment outputs; Phase B
    reduces only the usage deltas + admitted mask (the per-domain
    reduction tensors the layout confines DCN traffic to)."""
    hosts = dict(mesh.shape).get("hosts", 1)
    if hosts <= 1:
        return {"phase_a_gather": 0, "phase_b_reduce": 0}
    frac = (hosts - 1) / hosts
    phase_a = (W * 2            # fit + borrows (bool)
               + W * P * R * 4  # chosen (int32)
               + W * P * R      # chosen_borrow (bool)
               + W * F * R * 8)  # asg_usage (int64)
    phase_b = Q * F * R * 8 + C * F * R * 8 + W * 4
    return {"phase_a_gather": int(phase_a * frac),
            "phase_b_reduce": int(phase_b * frac)}


def probe(hosts_list, cqs_per_host: int, wl_per_host: int,
          cycles: int, seed: int) -> dict:
    import jax

    from kueue_tpu.parallel import domains
    from kueue_tpu.parallel.mesh import (make_host_mesh, plan_cycle,
                                         solve_cycle_sharded)
    devices = jax.devices()
    rows = []
    for h in hosts_list:
        if h > len(devices):
            rows.append({"hosts": h, "skipped":
                         f"only {len(devices)} devices"})
            continue
        mesh = make_host_mesh(devices[:h], hosts=h)
        # weak scaling: domains scale with hosts, domains/DEVICE constant
        topo, topo_dev, state, batch, wl, np = _build_inputs(
            num_cqs=cqs_per_host * h, num_cohorts=max(cqs_per_host // 4, 1) * h,
            num_workloads=wl_per_host * h, seed=seed)
        plan = plan_cycle(mesh, topo_dev, batch, topo_np=None)
        # round-robin baseline (the pre-planner `d mod n` layout) under
        # the SAME cost model — count x flavor width over the same
        # occupied-domain set — so the imbal columns are comparable
        n_dev = int(mesh.devices.size)
        dom = domains.workload_domains(batch.wl_cq, topo["cq_cohort"],
                                       topo["cohort_root"])
        D = len(topo["cohort_root"]) + len(topo["cq_cohort"])
        fw = domains.flavor_width(topo["offered"])
        weights = np.bincount(
            dom, weights=fw[np.asarray(batch.wl_cq)].astype(np.float64),
            minlength=D).astype(np.int64)
        occupied = np.flatnonzero(np.bincount(dom, minlength=D))
        naive_loads = np.zeros(n_dev, np.int64)
        np.add.at(naive_loads, occupied % n_dev, weights[occupied])
        times = []
        for c in range(max(cycles, 2)):
            t0 = time.perf_counter()
            out = solve_cycle_sharded(mesh, topo_dev, state, batch, 1,
                                      plan=plan)
            jax.block_until_ready(out["admitted"])
            times.append(time.perf_counter() - t0)
        warm = sorted(times[1:])  # drop the compile cycle
        W, P, R = batch.requests.shape
        Q, F, _ = topo["nominal"].shape
        C = topo["cohort_subtree"].shape[0]
        rows.append({
            "hosts": h,
            "devices": int(mesh.devices.size),
            "mesh_shape": dict(mesh.shape),
            "occupied_domains": plan.occupied,
            "domains_per_device": plan.occupied / mesh.devices.size,
            "columns_per_device": plan.d_cols,
            "planner_loads": plan.loads.tolist(),
            "planner_imbalance": plan.imbalance,
            "round_robin_imbalance": domains.imbalance_ratio(naive_loads),
            "plan_fingerprint": plan.fingerprint,
            "cycle_s_p50": warm[len(warm) // 2],
            "dcn_bytes_per_cycle": _dcn_bytes(mesh, W, P, R, F, Q, C),
            "admitted": int(np.asarray(out["admitted"]).sum()),
        })
    report = {"hosts": hosts_list, "rows": rows,
              "backend": jax.default_backend(),
              "total_devices": len(devices)}
    ran = [r for r in rows if "skipped" not in r]
    if ran:
        report["max_imbalance"] = max(r["planner_imbalance"] for r in ran)
        first, last = ran[0], ran[-1]
        if last["hosts"] > first["hosts"]:
            # weak scaling: per-cycle time growth vs total-domain growth
            growth = last["cycle_s_p50"] / max(first["cycle_s_p50"], 1e-9)
            domain_growth = last["hosts"] / first["hosts"]
            report["weak_scaling"] = {
                "cycle_time_growth": growth,
                "domain_growth": domain_growth,
                "sublinear": growth < domain_growth,
            }
    return report


def check_identity(hosts_list, seed: int, cases: int = 3) -> dict:
    """Randomized bit-identity: every mesh shape's admitted set, usage
    and cohort usage must equal the single-chip fused oracle's."""
    import jax
    import jax.numpy as jnp

    from kueue_tpu.parallel.mesh import make_host_mesh, solve_cycle_sharded
    from kueue_tpu.solver.kernel import max_rank_bound, solve_cycle_fused_impl
    devices = jax.devices()
    failures = []
    checked = 0
    for case in range(cases):
        topo, topo_dev, state, batch, wl, np = _build_inputs(
            num_cqs=24 + 8 * case, num_cohorts=6 + 2 * case,
            num_workloads=48 + 16 * case, seed=seed + case)
        mr = max_rank_bound(wl["wl_cq"], topo["cq_cohort"],
                            topo["cohort_root"])
        ref = solve_cycle_fused_impl(
            topo_dev, jnp.asarray(state.usage),
            jnp.asarray(state.cohort_usage), jnp.asarray(batch.requests),
            jnp.asarray(batch.podset_active), jnp.asarray(batch.wl_cq),
            jnp.asarray(batch.priority), jnp.asarray(batch.timestamp),
            jnp.asarray(batch.eligible), jnp.asarray(batch.solvable),
            num_podsets=1, max_rank=mr)
        for h in hosts_list:
            if h > len(devices):
                continue
            mesh = make_host_mesh(devices[:h], hosts=h)
            out = solve_cycle_sharded(mesh, topo_dev, state, batch, 1)
            checked += 1
            for key in ("admitted", "usage", "cohort_usage"):
                if not bool(jnp.array_equal(out[key], ref[key])):
                    failures.append({"case": case, "hosts": h, "key": key})
    return {"cases": cases, "shapes_checked": checked,
            "failures": failures}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    def opt(name, default):
        if name in argv:
            i = argv.index(name)
            val = argv[i + 1]
            del argv[i:i + 2]
            return val
        return default

    as_json = "--json" in argv
    identity = "--check-identity" in argv
    argv = [a for a in argv if a not in ("--json", "--check-identity")]
    hosts_list = [int(h) for h in opt("--hosts", "1,2,4,8").split(",")]
    n_devices = int(opt("--devices", str(max(hosts_list))))
    cqs_per_host = int(opt("--cqs-per-host", "64"))
    wl_per_host = int(opt("--wl-per-host", "128"))
    cycles = int(opt("--cycles", "4"))
    seed = int(opt("--seed", "0"))

    _force_devices(n_devices)  # before the first jax import

    report = probe(hosts_list, cqs_per_host, wl_per_host, cycles, seed)
    if identity:
        report["identity"] = check_identity(hosts_list, seed)

    if as_json:
        print(json.dumps(report), file=sys.stderr, flush=True)
    else:
        head = (f"{'hosts':>5} {'dev':>4} {'domains':>8} {'cols/dev':>8} "
                f"{'imbal':>6} {'rr-imbal':>8} {'cycle_p50':>10} "
                f"{'dcn_B(A/B)':>18}")
        lines = [head, "-" * len(head)]
        for r in report["rows"]:
            if "skipped" in r:
                lines.append(f"{r['hosts']:>5} skipped: {r['skipped']}")
                continue
            d = r["dcn_bytes_per_cycle"]
            lines.append(
                f"{r['hosts']:>5} {r['devices']:>4} "
                f"{r['occupied_domains']:>8} {r['columns_per_device']:>8} "
                f"{r['planner_imbalance']:>6.2f} "
                f"{r['round_robin_imbalance']:>8.2f} "
                f"{r['cycle_s_p50']:>10.4f} "
                f"{d['phase_a_gather']:>8}/{d['phase_b_reduce']}")
        if "weak_scaling" in report:
            ws = report["weak_scaling"]
            lines.append(f"weak scaling: cycle-time x{ws['cycle_time_growth']:.2f} "
                         f"over domains x{ws['domain_growth']:.0f} "
                         f"({'SUB' if ws['sublinear'] else 'SUPER'}-linear)")
        print("\n".join(lines), file=sys.stderr, flush=True)

    verdict = {
        "hosts": report["hosts"],
        "total_devices": report["total_devices"],
        "max_imbalance": report.get("max_imbalance"),
        "weak_scaling": report.get("weak_scaling"),
        "identity_failures": (report.get("identity", {}) or {}).get(
            "failures", []) if identity else None,
        "rows": [{k: r.get(k) for k in ("hosts", "devices",
                                        "occupied_domains",
                                        "planner_imbalance", "cycle_s_p50",
                                        "skipped")}
                 for r in report["rows"]],
    }
    ok = True
    if report.get("max_imbalance") is not None \
            and report["max_imbalance"] > IMBALANCE_GATE:
        ok = False
    if identity and verdict["identity_failures"]:
        ok = False
    verdict["ok"] = ok
    print(json.dumps(verdict))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
