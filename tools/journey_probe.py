"""Journey probe: end-to-end admission SLI + aging health for operators.

Drives the FULL control plane (KueueManager: sim store, controllers,
scheduler, journey ledger, aging watch) through a few traffic waves —
including an over-quota wave that forces requeue loops — then prints:

- a per-class time-to-admission table (count, p50, p99) folded from
  the SAME sealed journeys /metrics serves,
- the slowest retained exemplar's span timeline (the "why did it take
  N cycles" answer, read from the /debug/journeys producer),
- the aging watch's per-monitor verdicts.

Same CLI contract as tools/chaos_run.py / visibility_probe.py: the
human tables go to stderr (or --json for the full report), one
parseable JSON verdict line to stdout, exit non-zero when the probe
detects a violation — a ledger leak (retained journeys after
shutdown), an unstamped span, an incomplete slowest-exemplar timeline,
or an aging monitor in a leaking/over-bound verdict.

Usage: python tools/journey_probe.py [waves] [cqs] [--json]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402
from kueue_tpu.obs import DebugEndpoints  # noqa: E402
from kueue_tpu.obs.journey import CLASS_LABEL  # noqa: E402

DEFAULT_WAVES = 6
DEFAULT_CQS = 4

CLASSES = ("prod", "standard", "batch")


def make_objects(num_cqs: int):
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(num_cqs):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = "cohort-0"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=4000)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def make_workload(wave: int, i: int, n: int, now: float):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{n}", namespace="default", uid=f"wl-{n}",
        creation_timestamp=now,
        labels={CLASS_LABEL: CLASSES[n % len(CLASSES)]}))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 2000})]))))
    return wl


def probe(waves: int = DEFAULT_WAVES, num_cqs: int = DEFAULT_CQS) -> dict:
    from kueue_tpu.api.meta import Condition, set_condition
    from kueue_tpu.core import workload as wlpkg

    cfg = cfgpkg.Configuration()
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=cfg, clock=clock)
    # Burn-rate objectives so the evaluator runs (the probe's targets
    # are generous — the verdict gates on surface health, not speed).
    mgr.journey_ledger.set_objectives({c: 3600.0 for c in CLASSES})
    for obj in make_objects(num_cqs):
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)

    n = 0
    for wave in range(waves):
        # Each CQ gets 3 arrivals per wave at 2 cpu against 4-cpu
        # quota: one workload per wave requeues until earlier ones
        # finish — real requeue loops for the timelines.
        for i in range(num_cqs):
            for _ in range(3):
                mgr.store.create(make_workload(wave, i, n, clock.now()))
                n += 1
        for _ in range(3):
            mgr.run_until_idle(max_iterations=1_000_000)
            mgr.scheduler.schedule(timeout=0)
            mgr.run_until_idle(max_iterations=1_000_000)
            clock.advance(5.0)
        # Finish admitted workloads so the next wave's backlog drains.
        for wl in mgr.store.list("Workload"):
            if wlpkg.is_admitted(wl) and not wlpkg.is_finished(wl):
                set_condition(wl.status.conditions, Condition(
                    type=api.WORKLOAD_FINISHED, status="True",
                    reason="Succeeded", message="done"), clock.now())
                mgr.store.update(wl)
        mgr.run_until_idle(max_iterations=1_000_000)
    # Drain: cycle until the backlog admits.
    for _ in range(40):
        mgr.run_until_idle(max_iterations=1_000_000)
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle(max_iterations=1_000_000)
        clock.advance(5.0)
        for wl in mgr.store.list("Workload"):
            if wlpkg.is_admitted(wl) and not wlpkg.is_finished(wl):
                set_condition(wl.status.conditions, Condition(
                    type=api.WORKLOAD_FINISHED, status="True",
                    reason="Succeeded", message="done"), clock.now())
                mgr.store.update(wl)
        mgr.run_until_idle(max_iterations=1_000_000)

    led = mgr.journey_ledger
    metrics = mgr.metrics
    endpoints = DebugEndpoints(mgr.scheduler, metrics)
    status = led.status()
    payload = endpoints.handle("/debug/journeys", {"n": "1"})
    aging = endpoints.handle("/debug/aging", {})

    # Per-class TTA table from the SAME histogram the seal feeds.
    h = metrics.journey_tta_seconds
    classes = {}
    for cls in sorted({k[0] for k in h.series}):
        classes[cls] = {
            "count": h.count(cls=cls),
            "p50_s": round(h.percentile(0.5, cls=cls), 2),
            "p99_s": round(h.percentile(0.99, cls=cls), 2),
        }

    slowest = (payload.get("slowest") or [{}])[0]
    unstamped = status["unstamped_spans"]
    timeline_ok, timeline_why = False, "no slowest exemplar retained"
    if slowest:
        j = led.journey(slowest["workload"])
        if j is not None:
            timeline_ok, timeline_why = j.timeline_complete()

    report = {
        "waves": waves, "cqs": num_cqs, "submitted": n,
        "classes": classes,
        "journeys": {k: status[k] for k in
                     ("started", "completed", "requeues",
                      "requeues_per_admission", "lru_evictions",
                      "burn_rates")},
        "slowest": {k: slowest.get(k) for k in
                    ("workload", "tta_s", "requeues")} if slowest else None,
        "slowest_spans": slowest.get("spans", []),
        "timeline_ok": timeline_ok,
        "timeline_why": timeline_why,
        "unstamped_spans": unstamped,
        "aging_failing": aging["failing"],
        "aging": {name: mon["verdict"]
                  for name, mon in aging["monitors"].items()},
    }
    mgr.shutdown(checkpoint=False)
    report["retained_after_shutdown"] = led.retained
    return report


def render_table(report: dict) -> str:
    lines = ["per-class time-to-admission (sealed journeys)",
             f"{'class':>10} {'count':>6} {'p50_s':>8} {'p99_s':>8}"]
    for cls, row in report["classes"].items():
        lines.append(f"{cls:>10} {row['count']:>6} {row['p50_s']:>8} "
                     f"{row['p99_s']:>8}")
    j = report["journeys"]
    lines.append(f"journeys: {j['completed']}/{report['submitted']} sealed  "
                 f"requeues/admission: {j['requeues_per_admission']}  "
                 f"lru evictions: {j['lru_evictions']}")
    if report["slowest"]:
        s = report["slowest"]
        lines.append(f"slowest exemplar: {s['workload']} "
                     f"tta={s['tta_s']}s requeues={s['requeues']}")
        for sp in report["slowest_spans"]:
            extra = {k: v for k, v in sp.items()
                     if k not in ("kind", "t", "cycle", "generation",
                                  "route")}
            lines.append(f"  t={sp['t']:>10.1f} cycle={sp['cycle']:>4} "
                         f"gen={sp['generation']} {sp['kind']:<16} "
                         f"{extra if extra else ''}")
    lines.append("aging verdicts: " + ", ".join(
        f"{name}={v}" for name, v in report["aging"].items()))
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    waves = int(argv[0]) if len(argv) > 0 else DEFAULT_WAVES
    num_cqs = int(argv[1]) if len(argv) > 1 else DEFAULT_CQS
    report = probe(waves, num_cqs)
    if as_json:
        print(json.dumps(report), file=sys.stderr, flush=True)
    else:
        print(render_table(report), file=sys.stderr, flush=True)
    verdict = {k: v for k, v in report.items() if k != "slowest_spans"}
    verdict["ok"] = (report["retained_after_shutdown"] == 0
                     and report["unstamped_spans"] == 0
                     and report["timeline_ok"]
                     and report["journeys"]["completed"] > 0
                     and not report["aging_failing"])
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
