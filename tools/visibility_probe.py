"""Visibility probe: query-plane read-path health table for operators.

Drives the FULL control plane (KueueManager: sim store, controllers,
scheduler, snapshot-backed query plane) with `serve_visibility()` bound
to a real HTTP port, submits a few waves of traffic, and hammers the
pending-workloads endpoints from reader threads WHILE admission cycles
run — then prints one row per sample window:

    window  reads  qps  p50_ms  p99_ms  snap_age_s  token_lag  warm  err

plus a summary (total reads, latency percentiles, worst token lag vs
the live cache, warming-503 count) read from the same producers
/debug/queryplane serves, so the probe and the endpoint agree.

Same CLI contract as tools/chaos_run.py / transport_probe.py: the
human table (or --json report) goes to stderr, one parseable JSON
verdict line to stdout, exit non-zero when the probe detects a
read-plane violation — a response missing its generation stamp, worst
token lag above one structural generation, read errors, or leaked
snapshot handouts after shutdown.

Usage: python tools/visibility_probe.py [waves] [cqs] [readers] [--json]
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402

DEFAULT_WAVES = 6
DEFAULT_CQS = 8
DEFAULT_READERS = 2


def make_objects(num_cqs: int):
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(num_cqs):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % 2}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=4000)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def make_workload(wave: int, i: int, n: int):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{wave}-{i}", namespace="default", uid=f"wl-{wave}-{i}",
        creation_timestamp=float(n)))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 2000})]))))
    return wl


def probe(waves: int = DEFAULT_WAVES, num_cqs: int = DEFAULT_CQS,
          readers: int = DEFAULT_READERS) -> dict:
    cfg = cfgpkg.Configuration()
    clock = FakeClock(1000.0)
    mgr = KueueManager(cfg=cfg, clock=clock)
    for obj in make_objects(num_cqs):
        mgr.store.create(obj)
    mgr.run_until_idle(max_iterations=1_000_000)
    port = mgr.serve_visibility().port
    base = f"http://127.0.0.1:{port}"

    stop = threading.Event()
    lock = threading.Lock()
    stats = {"reads": 0, "warming": 0, "errors": 0, "unstamped": 0,
             "max_lag": 0, "lat": [], "windows": []}

    def one_read(k: int):
        cq = f"cq{k % num_cqs}"
        url = (f"{base}/apis/visibility.kueue.x-k8s.io/v1alpha1/"
               f"clusterqueues/{cq}/pendingworkloads?limit=20")
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                body = json.loads(resp.read())
        except urllib.error.HTTPError as err:
            with lock:
                if err.code == 503:
                    stats["warming"] += 1
                else:
                    stats["errors"] += 1
            return
        except Exception:
            with lock:
                stats["errors"] += 1
            return
        dt = time.perf_counter() - t0
        token = body.get("generation")
        lag = (mgr.cache.generation_lag(token)
               if token is not None else None)
        with lock:
            stats["reads"] += 1
            stats["lat"].append(dt)
            if token is None:
                stats["unstamped"] += 1
            elif lag > stats["max_lag"]:
                stats["max_lag"] = lag

    def reader(idx: int):
        k = idx
        while not stop.is_set():
            one_read(k)
            k += readers
            time.sleep(0.001)

    threads = [threading.Thread(target=reader, args=(i,), daemon=True)
               for i in range(readers)]
    for t in threads:
        t.start()

    n = 0
    try:
        for wave in range(waves):
            w0 = time.perf_counter()
            r0 = stats["reads"]
            for i in range(num_cqs):
                mgr.store.create(make_workload(wave, i, n))
                n += 1
            mgr.run_until_idle(max_iterations=1_000_000)
            mgr.scheduler.schedule(timeout=0)
            mgr.run_until_idle(max_iterations=1_000_000)
            clock.advance(1.0)
            dt = time.perf_counter() - w0
            with lock:
                wreads = stats["reads"] - r0
                lat = sorted(stats["lat"][-wreads:]) if wreads else []
            qp = mgr.query_plane.status()
            stats["windows"].append({
                "window": wave, "reads": wreads,
                "qps": round(wreads / max(dt, 1e-9), 1),
                "p50_ms": round(lat[len(lat) // 2] * 1e3, 2)
                if lat else None,
                "p99_ms": round(
                    lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3, 2)
                if lat else None,
                "snap_age_s": qp.get("age_s"),
                "token_lag": qp.get("token_lag"),
                "warming": stats["warming"], "errors": stats["errors"]})
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)

    lat = sorted(stats["lat"])

    def pct(q):
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(q * len(lat)))] * 1e3, 2)

    plane_status = mgr.query_plane.status()
    mgr.shutdown(checkpoint=False)
    report = {
        "waves": waves, "cqs": num_cqs, "readers": readers,
        "reads": stats["reads"], "warming_503s": stats["warming"],
        "errors": stats["errors"], "unstamped": stats["unstamped"],
        "read_p50_ms": pct(0.5), "read_p99_ms": pct(0.99),
        "max_token_lag": stats["max_lag"],
        "cycles_published": plane_status["cycles_published"],
        "tables_built": plane_status["tables_built"],
        "live_handouts_after_shutdown": mgr.cache.live_handouts,
        "windows": stats["windows"],
    }
    return report


def render_table(report: dict) -> str:
    head = (f"{'window':>6} {'reads':>6} {'qps':>8} {'p50_ms':>7} "
            f"{'p99_ms':>7} {'snap_age_s':>10} {'token_lag':>9} "
            f"{'warm':>5} {'err':>4}")
    lines = [head, "-" * len(head)]
    for w in report["windows"]:
        lines.append(
            f"{w['window']:>6} {w['reads']:>6} {w['qps']:>8} "
            f"{w['p50_ms'] if w['p50_ms'] is not None else '-':>7} "
            f"{w['p99_ms'] if w['p99_ms'] is not None else '-':>7} "
            f"{w['snap_age_s'] if w['snap_age_s'] is not None else '-':>10} "
            f"{w['token_lag'] if w['token_lag'] is not None else '-':>9} "
            f"{w['warming']:>5} {w['errors']:>4}")
    lines.append("-" * len(head))
    lines.append(
        f"reads: {report['reads']}  p50: {report['read_p50_ms']}ms  "
        f"p99: {report['read_p99_ms']}ms  max token lag: "
        f"{report['max_token_lag']}  warming 503s: "
        f"{report['warming_503s']}  errors: {report['errors']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    waves = int(argv[0]) if len(argv) > 0 else DEFAULT_WAVES
    num_cqs = int(argv[1]) if len(argv) > 1 else DEFAULT_CQS
    readers = int(argv[2]) if len(argv) > 2 else DEFAULT_READERS
    report = probe(waves, num_cqs, readers)
    if as_json:
        print(json.dumps(report), file=sys.stderr, flush=True)
    else:
        print(render_table(report), file=sys.stderr, flush=True)
    verdict = {k: v for k, v in report.items() if k != "windows"}
    verdict["ok"] = (report["errors"] == 0
                     and report["unstamped"] == 0
                     and report["max_token_lag"] <= 1
                     and report["reads"] > 0
                     and report["live_handouts_after_shutdown"] == 0)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
