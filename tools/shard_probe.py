"""Shard probe: sharded admission control plane health table.

Drives a ``ShardedControlPlane`` (RESILIENCE.md §9) — N leased
admission shards over one shared watch/store plane — through waves of
traffic, printing one row per wave:

    wave  created  admitted  per-shard admitted  backlog  epochs

Then exercises the two failure modes the subsystem exists for:

- a KILL/PROMOTE storm on one shard: the survivor keeps admitting its
  own cohorts during the outage, the dead shard's zombie token is
  fenced at the durable log (ONE write slipping through is a
  violation), and the promoted shard resumes admitting its cohorts
  within a bounded number of cycles (unbounded resume lag fails);
- a REBALANCE: a cohort unit is fenced away from its owner and
  reassigned; the new owner admits it, the old owner admits none of
  it, and the exactly-once cross-check holds throughout.

Exactly-once is checked two ways after every phase: the per-CQ cache
usage must match the store's admitted sum (a cross-shard double
admission double-counts usage), and the per-shard ``admitted_total``
counters must sum to the store's admitted workload count (an admission
counted by two shards makes the sum exceed the store).

Same CLI contract as tools/chaos_run.py / failover_probe.py: the human
table (or --json report) goes to stderr, one parseable JSON verdict
line to stdout, exit non-zero on a double admission, a leaked zombie
write, or unbounded resume lag.

Usage: python tools/shard_probe.py [waves] [shards] [cqs] [--json]
"""

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)  # for failover_probe when loaded by path
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu.api.meta import FakeClock  # noqa: E402
from kueue_tpu.parallel.shards import (  # noqa: E402
    SHARD_ACTIVE, ShardedControlPlane)
from kueue_tpu.sim.durable import Fenced  # noqa: E402

from failover_probe import (  # noqa: E402
    admitted_count, make_objects, make_workload, usage_consistent)

DEFAULT_WAVES = 6
DEFAULT_SHARDS = 2
DEFAULT_CQS = 6
MAX_CYCLES_TO_RESUME = 3


def exactly_once(scp) -> tuple:
    """The cross-shard exactly-once cross-check: cache usage must match
    the store's admitted sum AND the per-shard admission counters must
    sum to the store's admitted workload count."""
    ok, msg = usage_consistent(scp.plane)
    if not ok:
        return False, f"usage: {msg}"
    store_admitted = admitted_count(scp.plane)
    shard_sum = sum(s.admitted_total for s in scp.shards)
    if shard_sum != store_admitted:
        return False, (f"shard counters say {shard_sum} admissions, "
                       f"store says {store_admitted}")
    return True, ""


def probe(waves: int = DEFAULT_WAVES, n_shards: int = DEFAULT_SHARDS,
          num_cqs: int = DEFAULT_CQS) -> dict:
    clock = FakeClock(1000.0)
    scp = ShardedControlPlane(n_shards, clock=clock)
    for obj in make_objects(num_cqs):
        scp.plane.store.create(obj)
    scp.plane.run_until_idle(max_iterations=1_000_000)
    plan = scp.replan()

    windows = []
    n = 0
    consistency_failures = 0
    for wave in range(waves):
        for i in range(num_cqs):
            scp.plane.store.create(make_workload(wave, i, n))
            n += 1
        scp.plane.run_until_idle(max_iterations=1_000_000)
        scp.cycle()
        clock.advance(1.0)
        scp.renew_leases()
        ok, msg = exactly_once(scp)
        if not ok:
            consistency_failures += 1
        windows.append({
            "wave": wave, "created": num_cqs,
            "admitted": admitted_count(scp.plane),
            "per_shard": [s.admitted_total for s in scp.shards],
            "backlog": [scp.plane.queues.pending(cq) or 0
                        for cq in sorted(scp.plan.cq_shard)],
            "epochs": [s.token.epoch for s in scp.shards],
            "exactly_once": ok, "msg": msg})

    # --- the kill/promote storm on shard 0 ---------------------------
    victim = scp.shards[0]
    victim_cqs = set(plan.cqs_of(0))
    zombie = victim.token
    scp.kill_shard(0)

    # Survivor keeps admitting its OWN cohorts during the outage.
    survivor_before = [s.admitted_total for s in scp.shards]
    for i in range(num_cqs):
        scp.plane.store.create(make_workload(100, i, n))
        n += 1
    scp.plane.run_until_idle(max_iterations=1_000_000)
    scp.cycle()
    clock.advance(1.0)
    survivor_admitted = sum(
        s.admitted_total - b
        for s, b in zip(scp.shards[1:], survivor_before[1:]))
    dead_admitted = scp.shards[0].admitted_total - survivor_before[0]

    # Promote: the new incarnation resumes the dead shard's cohorts
    # within a bounded number of cycles (unbounded resume lag fails).
    # The lease epoch bumps FIRST — from here the dead holder's token
    # is a zombie and every write under it must fence (before the
    # takeover the lease is legitimately still the dead holder's;
    # that window is bounded by the lease duration, not tested here).
    promoted = scp.promote_shard(0)
    fenced_writes = 0
    leaked_writes = 0
    saved = scp.store.fencing
    scp.store.fencing = zombie
    try:
        try:
            scp.plane.store.create(make_workload(998, 0, 10_000))
            leaked_writes += 1
        except Fenced:
            fenced_writes += 1
    finally:
        scp.store.fencing = saved
    cycles_to_resume = None
    resume_before = scp.shards[0].admitted_total
    for cycle in range(MAX_CYCLES_TO_RESUME + 2):
        for i in range(num_cqs):
            scp.plane.store.create(make_workload(200 + cycle, i, n))
            n += 1
        scp.plane.run_until_idle(max_iterations=1_000_000)
        scp.cycle()
        clock.advance(1.0)
        if scp.shards[0].admitted_total > resume_before:
            cycles_to_resume = cycle + 1
            break
    ok_storm, storm_msg = exactly_once(scp)

    # --- the rebalance: move shard 0's first unit to shard 1 ----------
    moved_unit = plan.units_of(0)[0] if plan.units_of(0) else None
    rebalance_report = None
    rebalance_new_owner_delta = 0
    rebalance_old_owner_delta = 0
    if moved_unit is not None and n_shards > 1:
        rebalance_report = scp.rebalance(moved_unit, 1)
        before = [s.admitted_total for s in scp.shards]
        for i in range(num_cqs):
            scp.plane.store.create(make_workload(300, i, n))
            n += 1
        scp.plane.run_until_idle(max_iterations=1_000_000)
        for _ in range(2):
            scp.cycle()
            clock.advance(1.0)
        moved_cqs = set(scp.plan.cqs_of(1)) & victim_cqs
        rebalance_new_owner_delta = scp.shards[1].admitted_total - before[1]
        rebalance_old_owner_delta = sum(
            scp.shards[j].admitted_total - before[j]
            for j in range(n_shards)
            if not (set(scp.plan.cqs_of(j)) & moved_cqs) and j != 1)
    ok_final, final_msg = exactly_once(scp)

    report = {
        "waves": waves, "shards": n_shards, "cqs": num_cqs,
        "plan_fingerprint": plan.fingerprint,
        "plan_imbalance": plan.imbalance,
        "windows": windows,
        "consistency_failures": consistency_failures,
        "survivor_admitted_during_outage": survivor_admitted,
        "dead_shard_admissions": dead_admitted,
        "fenced_writes": fenced_writes,
        "leaked_writes": leaked_writes,
        "promoted_epoch": promoted.epoch,
        "cycles_to_resume": cycles_to_resume,
        "storm_exactly_once": ok_storm, "storm_msg": storm_msg,
        "rebalance": rebalance_report,
        "rebalance_new_owner_admitted": rebalance_new_owner_delta,
        "rebalance_old_owner_admitted": rebalance_old_owner_delta,
        "final_exactly_once": ok_final, "final_msg": final_msg,
        "status": scp.status(),
    }
    scp.shutdown()
    report["live_handouts_after_shutdown"] = scp.plane.cache.live_handouts
    return report


def render_table(report: dict) -> str:
    head = (f"{'wave':>5} {'created':>8} {'admitted':>9} "
            f"{'per-shard':>16} {'epochs':>10} {'ok':>3}")
    lines = [head, "-" * len(head)]
    for w in report["windows"]:
        lines.append(
            f"{w['wave']:>5} {w['created']:>8} {w['admitted']:>9} "
            f"{str(w['per_shard']):>16} {str(w['epochs']):>10} "
            f"{'y' if w['exactly_once'] else 'N':>3}")
    lines.append("-" * len(head))
    lines.append(
        f"storm: survivor admitted {report['survivor_admitted_during_outage']} "
        f"during outage  dead-shard admissions: "
        f"{report['dead_shard_admissions']}  fenced: "
        f"{report['fenced_writes']}  leaked: {report['leaked_writes']}")
    lines.append(
        f"promote: epoch {report['promoted_epoch']}  cycles to resume: "
        f"{report['cycles_to_resume']}  exactly-once: "
        f"{report['storm_exactly_once']}")
    reb = report["rebalance"]
    if reb:
        lines.append(
            f"rebalance: {reb['unit']} shard {reb['from']} -> "
            f"{reb['to']}  new-owner admitted: "
            f"{report['rebalance_new_owner_admitted']}  old-owner: "
            f"{report['rebalance_old_owner_admitted']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    waves = int(argv[0]) if len(argv) > 0 else DEFAULT_WAVES
    n_shards = int(argv[1]) if len(argv) > 1 else DEFAULT_SHARDS
    num_cqs = int(argv[2]) if len(argv) > 2 else DEFAULT_CQS
    report = probe(waves, n_shards, num_cqs)
    if as_json:
        print(json.dumps(report), file=sys.stderr, flush=True)
    else:
        print(render_table(report), file=sys.stderr, flush=True)
    verdict = {k: v for k, v in report.items()
               if k not in ("windows", "status")}
    verdict["ok"] = (
        report["consistency_failures"] == 0
        and report["survivor_admitted_during_outage"] > 0
        and report["dead_shard_admissions"] == 0
        and report["leaked_writes"] == 0
        and report["fenced_writes"] == 1
        and report["cycles_to_resume"] is not None
        and report["cycles_to_resume"] <= MAX_CYCLES_TO_RESUME
        and report["storm_exactly_once"]
        and report["rebalance_old_owner_admitted"] == 0
        and report["final_exactly_once"]
        and report["live_handouts_after_shutdown"] == 0)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
