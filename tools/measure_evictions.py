"""Measure eviction-issuing fan-out: sequential vs 8-way thread pool.

VERDICT r4 missing #1 asked for a measurement of the reference's 8-way
IssuePreemptions fan-out (preemption.go:195-235, parallelize.go:17-40)
against this repo's in-process store. The reference fans out to hide
apiserver round-trip latency; our store write is GIL-bound pure Python,
so the expectation is the pool only adds handoff overhead. This script
settles it empirically; Preemptor.eviction_workers carries the result.

Usage: python tools/measure_evictions.py [n_targets] [repeats]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.meta import FakeClock, ObjectMeta  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.core import workload as wlpkg  # noqa: E402
from kueue_tpu.scheduler.preemption import Preemptor, Target  # noqa: E402
from kueue_tpu.sim.runtime import EventRecorder  # noqa: E402
from kueue_tpu.sim.store import Store  # noqa: E402


def build(n):
    clock = FakeClock(1000.0)
    store = Store(clock)
    recorder = EventRecorder()
    targets = []
    for i in range(n):
        wl = api.Workload(metadata=ObjectMeta(
            name=f"victim-{i}", namespace="default", uid=f"wl-{i}",
            creation_timestamp=float(i)))
        wl.spec.queue_name = "lq"
        wl.spec.pod_sets.append(api.PodSet(
            name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
                containers=[Container(name="c",
                                      requests={"cpu": 1000})]))))
        admission = api.Admission(
            cluster_queue="cq",
            pod_set_assignments=[api.PodSetAssignment(
                name="main", flavors={"cpu": "f0"},
                resource_usage={"cpu": 1000}, count=1)])
        wlpkg.set_quota_reservation(wl, admission, 1000.0)
        store.create(wl)
        info = wlpkg.Info(store.get("Workload", "default", f"victim-{i}"))
        targets.append(Target(workload_info=info,
                              reason=api.IN_CLUSTER_QUEUE_REASON))

    def apply_preemption(wl, preempting_cq, reason, message):
        # Scheduler._apply_preemption's write path: clone + conditions +
        # store update + event.
        patch = wlpkg.clone_for_status_update(wl)
        now = clock.now()
        wlpkg.set_evicted_condition(patch, api.EVICTED_BY_PREEMPTION,
                                    message, now)
        wlpkg.set_preempted_condition(patch, reason, message, now)
        store.update_status(patch, owned_status=True)
        recorder.event(patch, "Normal", "Preempted", message)

    preemptor = Preemptor(clock=clock, apply_preemption=apply_preemption)
    pre_info = wlpkg.Info(api.Workload(metadata=ObjectMeta(
        name="preemptor", namespace="default", uid="wl-pre")))
    pre_info.cluster_queue = "cq"
    return preemptor, pre_info, targets


def measure(workers, n, repeats):
    times = []
    for _ in range(repeats):
        preemptor, pre_info, targets = build(n)
        preemptor.eviction_workers = workers
        t0 = time.perf_counter()
        issued = preemptor.issue_preemptions(pre_info, targets)
        times.append(time.perf_counter() - t0)
        assert issued == n
    times.sort()
    return times[len(times) // 2]


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    repeats = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    measure(8, 64, 2)  # warm the pool + code paths
    seq = measure(1, n, repeats)
    par = measure(8, n, repeats)
    print(json.dumps({
        "measurement": "eviction_issuing", "targets": n,
        "sequential_ms": round(seq * 1e3, 1),
        "workers8_ms": round(par * 1e3, 1),
        "fanout_speedup": round(seq / par, 2),
        "verdict": "fan-out wins" if par < seq else
                   "sequential wins (GIL-bound in-process store)",
    }))


if __name__ == "__main__":
    main()
