"""Failover probe: hot-standby replication + fencing health table.

Drives a fenced leader (KueueManager over a durable checkpoint/WAL
log, ``resilience/replica.lead``) through waves of traffic while a
``StandbyReplica`` tails the WAL, printing one row per wave:

    wave  appends  lag_pre  lag_post  applied  lag_s  epoch

Then simulates the failure the subsystem exists for — as a PARTITION,
not a crash, because that is the sharper case: the old leader is still
ALIVE when the standby force-promotes. The probe verifies the fencing
contract end-to-end (RESILIENCE.md §7):

- the deposed leader's store writes raise ``Fenced`` (counted; ONE
  write slipping through is a violation),
- the deposed leader's admission cycles admit nothing (its leader
  gate reads the bumped epoch),
- the promoted replica admits within a bounded number of cycles and
  its per-CQ cache usage matches the store's admitted sum (the
  double-admission cross-check),
- replication lag drains to zero at every poll (unbounded lag fails).

Same CLI contract as tools/chaos_run.py / visibility_probe.py: the
human table (or --json report) goes to stderr, one parseable JSON
verdict line to stdout, exit non-zero on unbounded lag or a fencing
violation.

Usage: python tools/failover_probe.py [waves] [cqs] [--json]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_tpu import config as cfgpkg  # noqa: E402
from kueue_tpu.api import kueue as api  # noqa: E402
from kueue_tpu.api.corev1 import (  # noqa: E402
    Container, PodSpec, PodTemplateSpec)
from kueue_tpu.api.meta import FakeClock, LabelSelector, ObjectMeta  # noqa: E402
from kueue_tpu.core import workload as wlpkg  # noqa: E402
from kueue_tpu.manager import KueueManager  # noqa: E402
from kueue_tpu.resilience.replica import StandbyReplica, lead  # noqa: E402
from kueue_tpu.sim.durable import Fenced  # noqa: E402

DEFAULT_WAVES = 6
DEFAULT_CQS = 6
MAX_CYCLES_TO_ADMIT = 3


def make_objects(num_cqs: int):
    rf = api.ResourceFlavor(metadata=ObjectMeta(name="f0", uid="rf-f0"))
    out = [rf]
    for i in range(num_cqs):
        cq = api.ClusterQueue(metadata=ObjectMeta(name=f"cq{i}",
                                                  uid=f"cq-{i}"))
        cq.spec.namespace_selector = LabelSelector()
        cq.spec.cohort = f"cohort-{i % 2}"
        cq.spec.resource_groups.append(api.ResourceGroup(
            covered_resources=["cpu"],
            flavors=[api.FlavorQuotas(name="f0", resources=[
                api.ResourceQuota(name="cpu", nominal_quota=100_000)])]))
        lq = api.LocalQueue(metadata=ObjectMeta(
            name=f"lq{i}", namespace="default", uid=f"lq-{i}"))
        lq.spec.cluster_queue = f"cq{i}"
        out += [cq, lq]
    return out


def make_workload(wave: int, i: int, n: int):
    wl = api.Workload(metadata=ObjectMeta(
        name=f"w{wave}-{i}", namespace="default", uid=f"wl-{wave}-{i}",
        creation_timestamp=float(n)))
    wl.spec.queue_name = f"lq{i}"
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": 2000})]))))
    return wl


def usage_consistent(mgr) -> tuple:
    expected: dict = {}
    for wl in mgr.store.list("Workload", copy_objects=False):
        if not wlpkg.has_quota_reservation(wl):
            continue
        info = wlpkg.Info(wl)
        cq = wl.status.admission.cluster_queue
        bucket = expected.setdefault(cq, {})
        for fr, v in info.flavor_resource_usage().items():
            bucket[fr] = bucket.get(fr, 0) + v
    for cq in mgr.cache.hm.cluster_queues:
        reserved, _ = mgr.cache.usage_for_cluster_queue(cq)
        want = {fr: v for fr, v in expected.get(cq, {}).items() if v}
        got = {fr: v for fr, v in reserved.items() if v}
        if want != got:
            return False, f"{cq}: store says {want}, cache says {got}"
    return True, ""


def admitted_count(mgr) -> int:
    return sum(1 for wl in mgr.store.list("Workload", copy_objects=False)
               if wlpkg.has_quota_reservation(wl))


def probe(waves: int = DEFAULT_WAVES, num_cqs: int = DEFAULT_CQS) -> dict:
    cfg = cfgpkg.Configuration()
    cfg.store.durable = True
    cfg.store.checkpoint_every = 64
    clock = FakeClock(1000.0)
    leader = KueueManager(cfg=cfg, clock=clock)
    for obj in make_objects(num_cqs):
        leader.store.create(obj)
    leader.run_until_idle(max_iterations=1_000_000)
    durable = leader.durable
    token = lead(leader, durable, identity="leader-0")
    standby = StandbyReplica(durable, clock=clock, identity="standby-0")

    windows = []
    n = 0
    unbounded_lag = 0
    for wave in range(waves):
        appends0 = durable.appends
        for i in range(num_cqs):
            leader.store.create(make_workload(wave, i, n))
            n += 1
        leader.run_until_idle(max_iterations=1_000_000)
        leader.scheduler.schedule(timeout=0)
        leader.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        token.renew(clock.now())
        lag_pre = standby.lag_records
        standby.poll()
        lag_post = standby.lag_records
        if lag_post is None or lag_post != 0:
            # The tail must DRAIN at every poll — anything else means
            # the follower cannot keep up with one cycle's appends
            # (unbounded lag, the probe's failure condition).
            unbounded_lag += 1
        windows.append({
            "wave": wave, "appends": durable.appends - appends0,
            "lag_pre": lag_pre, "lag_post": lag_post,
            "applied": standby.applied_records,
            "lag_s": round(standby.lag_seconds, 3),
            "epoch": durable.fencing_epoch})

    pre_admitted = admitted_count(leader)

    # --- the partition: promote OVER a live leader --------------------
    promoted = standby.promote(force=True)

    # Deposed-leader commit attempts: every one must raise Fenced.
    fenced_writes = 0
    leaked_writes = 0
    try:
        leader.store.create(make_workload(999, 0, 10_000))
        leaked_writes += 1
    except Fenced:
        fenced_writes += 1
    try:
        wl = leader.store.list("Workload", copy_objects=False)[0]
        patch = wlpkg.clone_for_status_update(wl)
        patch.status.conditions = list(patch.status.conditions)
        from kueue_tpu.api.meta import Condition, set_condition
        set_condition(patch.status.conditions, Condition(
            type="DeposedProbe", status="True", reason="Probe",
            message="deposed status write"), clock.now())
        leader.store.update_status(patch, owned_status=True)
        leaked_writes += 1
    except Fenced:
        fenced_writes += 1
    # Deposed admission cycles: the leader gate reads the bumped epoch.
    deposed_before = admitted_count(leader)
    leader.scheduler.schedule(timeout=0)
    deposed_admissions = admitted_count(leader) - deposed_before

    # The promoted replica keeps admitting the live traffic.
    cycles_to_admit = None
    before = admitted_count(promoted)
    for cycle in range(MAX_CYCLES_TO_ADMIT + 2):
        for i in range(num_cqs):
            promoted.store.create(make_workload(100 + cycle, i, n))
            n += 1
        promoted.run_until_idle(max_iterations=1_000_000)
        promoted.scheduler.schedule(timeout=0)
        promoted.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        if admitted_count(promoted) > before:
            cycles_to_admit = cycle + 1
            break
    ok_usage, usage_msg = usage_consistent(promoted)

    report = {
        "waves": waves, "cqs": num_cqs,
        "windows": windows,
        "unbounded_lag_polls": unbounded_lag,
        "max_lag_records": standby.max_lag_records,
        "resyncs": standby.resyncs,
        "pre_partition_admitted": pre_admitted,
        "promotion": (standby.last_promotion.to_dict()
                      if standby.last_promotion else None),
        "fencing_epoch": durable.fencing_epoch,
        "fenced_writes": fenced_writes,
        "leaked_writes": leaked_writes,
        "deposed_admissions": deposed_admissions,
        "cycles_to_first_admission": cycles_to_admit,
        "usage_consistent": ok_usage, "usage_msg": usage_msg,
        "standby_status": standby.status(),
    }
    promoted.shutdown(checkpoint=False)
    report["live_handouts_after_shutdown"] = promoted.cache.live_handouts
    return report


def render_table(report: dict) -> str:
    head = (f"{'wave':>5} {'appends':>8} {'lag_pre':>8} {'lag_post':>9} "
            f"{'applied':>8} {'lag_s':>6} {'epoch':>6}")
    lines = [head, "-" * len(head)]
    for w in report["windows"]:
        lines.append(
            f"{w['wave']:>5} {w['appends']:>8} "
            f"{w['lag_pre'] if w['lag_pre'] is not None else '-':>8} "
            f"{w['lag_post'] if w['lag_post'] is not None else '-':>9} "
            f"{w['applied']:>8} {w['lag_s']:>6} {w['epoch']:>6}")
    lines.append("-" * len(head))
    prom = report["promotion"] or {}
    lines.append(
        f"promotion: {prom.get('duration_s', 0) * 1e3:.1f}ms at epoch "
        f"{prom.get('epoch')}  drained: {prom.get('drained_records')}  "
        f"fenced writes: {report['fenced_writes']}  leaked: "
        f"{report['leaked_writes']}  deposed admissions: "
        f"{report['deposed_admissions']}")
    lines.append(
        f"max lag: {report['max_lag_records']} records  unbounded-lag "
        f"polls: {report['unbounded_lag_polls']}  cycles to first "
        f"admission: {report['cycles_to_first_admission']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    waves = int(argv[0]) if len(argv) > 0 else DEFAULT_WAVES
    num_cqs = int(argv[1]) if len(argv) > 1 else DEFAULT_CQS
    report = probe(waves, num_cqs)
    if as_json:
        print(json.dumps(report), file=sys.stderr, flush=True)
    else:
        print(render_table(report), file=sys.stderr, flush=True)
    verdict = {k: v for k, v in report.items()
               if k not in ("windows", "standby_status")}
    verdict["ok"] = (
        report["unbounded_lag_polls"] == 0
        and report["leaked_writes"] == 0
        and report["deposed_admissions"] == 0
        and report["fenced_writes"] == 2
        and report["cycles_to_first_admission"] is not None
        and report["cycles_to_first_admission"] <= MAX_CYCLES_TO_ADMIT
        and report["usage_consistent"]
        and report["live_handouts_after_shutdown"] == 0)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
