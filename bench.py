"""Benchmark: batched admission on TPU — honest, production-path numbers.

Measures three things at the north-star shape (BASELINE.json: 2k
ClusterQueues x 32 flavors, 2048 heads/cycle):

1. kernel: the global-scan solve_cycle AND the production
   solve_cycle_cohort_parallel (solver-only device time),
2. end-to-end: full Scheduler.schedule cycles with BatchSolver over the
   real object model — heads pop, snapshot deep-copy, encode, device
   solve, decode, admit, requeue (the number a user actually sees),
3. a preemption-heavy cycle: admitted victims + pending preemptors,
   resolved by the batched device preemption path vs the CPU preemptor.

Baseline: the reference's scheduler scalability harness admits 15,000
workloads in 351.1s (BASELINE.md) ~= 42.7 admitted/s for the sequential
Go scheduler. vs_baseline is our END-TO-END admitted/s over that.

Prints ONE JSON line (the flagship end-to-end metric) on stdout;
supplementary metrics go to stderr as labeled JSON lines.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_CQS = 2048
NUM_COHORTS = 256
NUM_FLAVORS = 32
NUM_RESOURCES = 2
HEADS = 2048


def log(obj):
    print(json.dumps(obj), file=sys.stderr)


def p50(times):
    times = sorted(times)
    return times[len(times) // 2]


# -- object-model scenario builders (self-contained) ----------------------

def make_flavor(name):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import ObjectMeta
    return api.ResourceFlavor(metadata=ObjectMeta(name=name, uid=f"rf-{name}"))


def make_cq(name, cohort, flavors, nominal_units, preemption=None):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import LabelSelector, ObjectMeta
    cq = api.ClusterQueue(metadata=ObjectMeta(name=name, uid=f"cq-{name}"))
    cq.spec.namespace_selector = LabelSelector()
    cq.spec.cohort = cohort
    if preemption is not None:
        cq.spec.preemption = preemption
    fqs = []
    for f in flavors:
        fqs.append(api.FlavorQuotas(name=f, resources=[
            api.ResourceQuota(name="cpu", nominal_quota=nominal_units * 1000),
            api.ResourceQuota(name="memory", nominal_quota=nominal_units << 30),
        ]))
    cq.spec.resource_groups.append(api.ResourceGroup(
        covered_resources=["cpu", "memory"], flavors=fqs))
    return cq


def make_lq(name, cq):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import ObjectMeta
    lq = api.LocalQueue(metadata=ObjectMeta(name=name, namespace="default",
                                            uid=f"lq-{name}"))
    lq.spec.cluster_queue = cq
    return lq


def make_workload(name, queue, cpu_units, priority=0, creation=0.0):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
    from kueue_tpu.api.meta import ObjectMeta
    wl = api.Workload(metadata=ObjectMeta(
        name=name, namespace="default", uid=f"wl-{name}",
        creation_timestamp=creation))
    wl.spec.queue_name = queue
    wl.spec.priority = priority
    spec = PodSpec(containers=[Container(
        name="c", requests={"cpu": cpu_units * 1000, "memory": cpu_units << 30})])
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=spec)))
    return wl


class BenchClient:
    """Minimal SchedulerClient: counts admissions, no store."""

    def __init__(self):
        self.admitted = 0
        self.evicted = 0

    def namespace_labels(self, namespace):
        return {}

    def limit_ranges(self, namespace):
        return []

    def apply_admission(self, wl):
        from kueue_tpu.core import workload as wlpkg
        if wlpkg.is_evicted(wl):
            self.evicted += 1
        else:
            self.admitted += 1

    def patch_not_admitted(self, wl):
        pass

    def event(self, wl, event_type, reason, message):
        pass


def build_env(num_cqs, num_cohorts, flavors, nominal_units, solver=None,
              preemption=None):
    from kueue_tpu.api.meta import FakeClock
    from kueue_tpu.cache import Cache
    from kueue_tpu.queue import Manager
    from kueue_tpu.scheduler.scheduler import Scheduler
    clock = FakeClock(1000.0)
    cache = Cache()
    queues = Manager(clock=clock)
    client = BenchClient()
    sched = Scheduler(queues, cache, client, clock=clock, solver=solver,
                      solver_min_heads=0)
    for f in flavors:
        cache.add_or_update_resource_flavor(make_flavor(f))
    for i in range(num_cqs):
        cq = make_cq(f"cq{i}", f"cohort-{i % num_cohorts}", flavors,
                     nominal_units, preemption=preemption)
        cache.add_cluster_queue(cq)
        queues.add_cluster_queue(cq)
        queues.add_local_queue(make_lq(f"lq{i}", f"cq{i}"))
    return sched, cache, queues, client, clock


# -- benchmarks -----------------------------------------------------------

def bench_kernel():
    import jax
    import jax.numpy as jnp

    from kueue_tpu.solver.kernel import (
        max_rank_bound, solve_cycle, solve_cycle_fused)
    from kueue_tpu.solver.synth import synth_solver_inputs

    topo, usage, cohort_usage, wl = synth_solver_inputs(
        num_cqs=NUM_CQS, num_cohorts=NUM_COHORTS, num_flavors=NUM_FLAVORS,
        num_resources=NUM_RESOURCES, num_workloads=HEADS, seed=42)
    topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}
    args = (jnp.asarray(usage), jnp.asarray(cohort_usage),
            jnp.asarray(wl["requests"]), jnp.asarray(wl["podset_active"]),
            jnp.asarray(wl["wl_cq"]), jnp.asarray(wl["priority"]),
            jnp.asarray(wl["timestamp"]), jnp.asarray(wl["eligible"]),
            jnp.asarray(wl["solvable"]))

    from functools import partial

    from kueue_tpu.solver.kernel import solve_cycle_fused_impl, solve_cycle_impl

    max_rank = max_rank_bound(wl["wl_cq"], topo["cq_cohort"],
                              topo["cohort_root"])

    # measure the tunnel/dispatch round-trip floor with a trivial op
    triv = jax.jit(lambda a: a + 1)
    import numpy as np
    int(np.asarray(triv(jnp.ones(8, jnp.int32))).sum())
    t_rt = []
    for _ in range(5):
        t0 = time.perf_counter()
        int(np.asarray(triv(jnp.ones(8, jnp.int32))).sum())
        t_rt.append(time.perf_counter() - t0)
    rt_ms = p50(t_rt) * 1e3

    def run_global():
        return solve_cycle(topo_dev, *args, num_podsets=1)

    def run_cp():
        return solve_cycle_fused(topo_dev, *args, num_podsets=1,
                                 max_rank=max_rank)

    def sync(out):
        return int(np.asarray(out["admitted"]).sum())

    admitted = sync(run_global())
    t_global = []
    for _ in range(8):
        t0 = time.perf_counter()
        sync(run_global())
        t_global.append(time.perf_counter() - t0)

    admitted_cp = sync(run_cp())
    t_cp = []
    for _ in range(8):
        t0 = time.perf_counter()
        sync(run_cp())
        t_cp.append(time.perf_counter() - t0)
    assert admitted == admitted_cp, (admitted, admitted_cp)

    # device-compute isolation: run N chained solves in ONE dispatch (an
    # output->input data dependence stops XLA hoisting), so the
    # per-cycle device time excludes the host round-trip entirely
    def chained(impl_kwargs, impl, n):
        def body(i, prio):
            out = impl(topo_dev, *args[:5], prio, *args[6:], **impl_kwargs)
            return prio + out["admitted"].astype(jnp.int64)
        return jax.lax.fori_loop(0, n, body, args[5])

    def device_per_cycle(impl, **impl_kwargs):
        fn = jax.jit(partial(chained, impl_kwargs, impl), static_argnums=0)
        ts = {}
        for n in (1, 17):
            int(np.asarray(fn(n)).sum())  # compile + warm
            t0 = time.perf_counter()
            int(np.asarray(fn(n)).sum())
            ts[n] = time.perf_counter() - t0
        return max(0.0, (ts[17] - ts[1]) / 16)

    dev_global = device_per_cycle(solve_cycle_impl, num_podsets=1)
    dev_fused = device_per_cycle(solve_cycle_fused_impl, num_podsets=1,
                                 max_rank=max_rank)

    log({"bench": "device_round_trip_floor", "p50_ms": round(rt_ms, 1)})
    log({"bench": "kernel_global_scan", "p50_ms": round(p50(t_global) * 1e3, 2),
         "device_only_ms": round(dev_global * 1e3, 3),
         "admitted_per_cycle": admitted})
    log({"bench": "kernel_fused_cohort_parallel", "max_rank": max_rank,
         "p50_ms": round(p50(t_cp) * 1e3, 2),
         "device_only_ms": round(dev_fused * 1e3, 3),
         "admitted_per_cycle": admitted_cp,
         "device_speedup_vs_global": round(dev_global / max(dev_fused, 1e-9), 1)})
    return p50(t_cp), admitted_cp


def bench_e2e(cycles=5):
    """Full Scheduler.schedule with BatchSolver: heads + snapshot +
    encode + device solve + decode + admit + requeue."""
    from kueue_tpu.solver import BatchSolver

    flavors = [f"f{i}" for i in range(NUM_FLAVORS)]
    sched, cache, queues, client, clock = build_env(
        NUM_CQS, NUM_COHORTS, flavors, nominal_units=40, solver=BatchSolver())

    # 1 head per CQ per cycle: submit cycles+1 waves
    n = 0
    for wave in range(cycles + 1):
        for i in range(NUM_CQS):
            wl = make_workload(f"w{wave}-{i}", f"lq{i}", cpu_units=4,
                               priority=n % 5, creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1

    # warmup cycle (compiles the bucketed shapes)
    sched.schedule(timeout=0)
    times = []
    for _ in range(cycles):
        before = client.admitted
        t0 = time.perf_counter()
        sched.schedule(timeout=0)
        times.append(time.perf_counter() - t0)
        assert client.admitted > before
    per_cycle = client.admitted / (cycles + 1)
    tp50 = p50(times)
    log({"bench": "e2e_schedule_with_solver", "p50_ms": round(tp50 * 1e3, 1),
         "admitted_per_cycle": round(per_cycle),
         "admitted_per_sec": round(per_cycle / tp50, 1)})
    return tp50, per_cycle


def bench_e2e_cpu(cycles=3):
    """The same end-to-end cycle on the pure-CPU path, for the honest
    internal comparison."""
    flavors = [f"f{i}" for i in range(NUM_FLAVORS)]
    sched, cache, queues, client, clock = build_env(
        NUM_CQS, NUM_COHORTS, flavors, nominal_units=40, solver=None)
    n = 0
    for wave in range(cycles + 1):
        for i in range(NUM_CQS):
            wl = make_workload(f"w{wave}-{i}", f"lq{i}", cpu_units=4,
                               priority=n % 5, creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1
    sched.schedule(timeout=0)
    times = []
    for _ in range(cycles):
        t0 = time.perf_counter()
        sched.schedule(timeout=0)
        times.append(time.perf_counter() - t0)
    per_cycle = client.admitted / (cycles + 1)
    tp50 = p50(times)
    log({"bench": "e2e_schedule_cpu_only", "p50_ms": round(tp50 * 1e3, 1),
         "admitted_per_sec": round(per_cycle / tp50, 1)})
    return tp50


def bench_preemption(num_cqs=256, num_cohorts=32, victims_per_cq=4):
    """Preemption-heavy cycle: every CQ is full of low-priority admitted
    workloads; one high-priority preemptor per CQ forces target
    selection. Device batch vs CPU preemptor."""
    from kueue_tpu.api import kueue as api
    from kueue_tpu.core import workload as wlpkg
    from kueue_tpu.solver import BatchSolver

    preemption = api.ClusterQueuePreemption(
        within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
        reclaim_within_cohort=api.PREEMPTION_ANY)
    def build(solver):
        sched, cache, queues, client, clock = build_env(
            num_cqs, num_cohorts, ["f0"], nominal_units=8, solver=solver,
            preemption=preemption)
        for i in range(num_cqs):
            for v in range(victims_per_cq):
                wl = make_workload(f"victim{i}-{v}", f"lq{i}", cpu_units=2,
                                   priority=0, creation=float(v))
                admission = api.Admission(
                    cluster_queue=f"cq{i}",
                    pod_set_assignments=[api.PodSetAssignment(
                        name="main", flavors={"cpu": "f0", "memory": "f0"},
                        resource_usage={"cpu": 2000, "memory": 2 << 30},
                        count=1)])
                wlpkg.set_quota_reservation(wl, admission, float(v))
                cache.add_or_update_workload(wl)
            queues.add_or_update_workload(
                make_workload(f"preemptor{i}", f"lq{i}", cpu_units=4,
                              priority=10, creation=1000.0))
        return sched, client

    out = {}
    for label, mk in (("cpu", lambda: None), ("device", BatchSolver)):
        # warmup run compiles the bucketed shapes; the timed run rebuilds
        # the identical scenario so the jit cache is hot
        sched, client = build(mk())
        sched.schedule(timeout=0)
        sched, client = build(mk())
        t0 = time.perf_counter()
        sched.schedule(timeout=0)
        dt = time.perf_counter() - t0
        out[label] = (dt, client.evicted, sched.preemption_fallbacks)
    (t_cpu, ev_cpu, _), (t_dev, ev_dev, fb) = out["cpu"], out["device"]
    assert ev_cpu == ev_dev and ev_dev > 0 and fb == 0, (ev_cpu, ev_dev, fb)
    log({"bench": "preemption_heavy_cycle", "cqs": num_cqs,
         "evictions": ev_dev, "cpu_ms": round(t_cpu * 1e3, 1),
         "device_ms": round(t_dev * 1e3, 1),
         "speedup": round(t_cpu / t_dev, 2)})
    return t_dev, ev_dev


def main():
    import jax
    log({"devices": [str(d) for d in jax.devices()]})

    solver_p50, _ = bench_kernel()
    e2e_p50, per_cycle = bench_e2e()
    bench_e2e_cpu()
    bench_preemption()

    admitted_per_sec = per_cycle / e2e_p50
    baseline = 15000.0 / 351.1  # reference harness admitted/s, BASELINE.md
    print(json.dumps({
        "metric": "e2e_admitted_workloads_per_sec_2048cq_32flavor",
        "value": round(admitted_per_sec, 1),
        "unit": "workloads/s",
        "vs_baseline": round(admitted_per_sec / baseline, 2),
    }))


if __name__ == "__main__":
    main()
