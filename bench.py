"""Benchmark: batched admission-cycle throughput on TPU.

Measures the north-star scenario from BASELINE.json: one admission cycle
over the head-of-queue of 2k ClusterQueues x 32 flavors (the reference
pops <=1 head per CQ per cycle), reporting cycle latency and
workloads-admitted/sec.

Baseline: the reference's scheduler scalability harness admits 15,000
workloads in 351.1s on its CI scenario (BASELINE.md) ~= 42.7 admitted
workloads/sec for the sequential Go scheduler. vs_baseline is our
admitted/sec over that number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import jax.numpy as jnp

    from kueue_tpu.solver.kernel import solve_cycle
    from kueue_tpu.solver.synth import synth_solver_inputs

    # North-star shape: 2k CQs x 32 flavors; 2048 heads/cycle.
    topo, usage, cohort_usage, wl = synth_solver_inputs(
        num_cqs=2048, num_cohorts=256, num_flavors=32, num_resources=2,
        num_workloads=2048, seed=42)
    topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}
    args = (jnp.asarray(usage), jnp.asarray(cohort_usage),
            jnp.asarray(wl["requests"]), jnp.asarray(wl["podset_active"]),
            jnp.asarray(wl["wl_cq"]), jnp.asarray(wl["priority"]),
            jnp.asarray(wl["timestamp"]), jnp.asarray(wl["eligible"]),
            jnp.asarray(wl["solvable"]))

    def run():
        return solve_cycle(topo_dev, *args, num_podsets=1)

    # compile + warmup
    result = run()
    jax.block_until_ready(result)
    admitted_per_cycle = int(result["admitted"].sum())

    times = []
    for _ in range(20):
        t0 = time.perf_counter()
        out = run()
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    p50 = times[len(times) // 2]

    admitted_per_sec = admitted_per_cycle / p50
    baseline_admitted_per_sec = 15000.0 / 351.1  # reference harness, BASELINE.md
    print(json.dumps({
        "metric": "admitted_workloads_per_sec_2048cq_32flavor_cycle",
        "value": round(admitted_per_sec, 1),
        "unit": "workloads/s",
        "vs_baseline": round(admitted_per_sec / baseline_admitted_per_sec, 2),
    }))
    print(f"# cycle p50 latency: {p50*1000:.2f} ms, "
          f"admitted/cycle: {admitted_per_cycle}, devices: {jax.devices()}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
