"""Benchmark: batched admission on TPU — honest, production-path numbers.

Scenarios at the north-star shape (BASELINE.json: 2k ClusterQueues x 32
flavors, 2048 heads/cycle), each run end-to-end through the full
Scheduler.schedule cycle over the real object model (heads pop, snapshot
deep-copy, encode, device solve, decode, admit, requeue):

1. kernel: the global-scan solve_cycle AND the production fused kernel
   (solver-only device time + the measured tunnel round-trip floor),
2. e2e progressive fill (FLAGSHIP): 33 waves of flavor-sized workloads
   drive every CQ from empty to a fully loaded 32-deep flavor list —
   covering both the shallow regime (the sequential assigner's best
   case) and the contention regime it degrades in; the solver side runs
   the PRODUCTION config (device-resident state + pipelined dispatch),
3. e2e shallow: the first-flavor-always-fits best case for the CPU
   path, kept for honesty,
4. fair sharing (steady state with completions): DRF share ordering for
   the full batch each cycle; the device side runs the adaptive engine
   router (its win here is routing around the device),
5. fair preemption: the DRF-heap fairPreemptions loop under the routed
   config,
6. preemption small: 4-candidate within-CQ problems — the work gate must
   route these to the CPU preemptor (speedup ~1.0 is the win),
7. preemption heavy: hierarchical-cohort (depth-2 chains) cohort-wide
   reclaim with ~500-candidate problems and deep remove/fill-back —
   the batched device preemptor's regime,
8. depth-4 cohort chains: prices the kernel's unrolled chain walks,
9. routed_system_blended: geometric mean over the row mix — the one
   number for "the routed system vs the sequential scheduler".

Baseline: the reference's scheduler scalability harness admits 15,000
workloads in 351.1s (BASELINE.md) ~= 42.7 admitted/s for the sequential
Go scheduler. vs_baseline is our END-TO-END admitted/s over that.

Prints ONE JSON line (the flagship end-to-end metric) on stdout;
supplementary metrics go to stderr as labeled JSON lines.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from kueue_tpu.utils.runtime import enable_compilation_cache, tune_gc

tune_gc()  # manager-binary GC profile (applies to both measured paths)
enable_compilation_cache()  # amortize remote compiles across runs

NUM_CQS = 2048
NUM_COHORTS = 256
NUM_FLAVORS = 32
NUM_RESOURCES = 2
HEADS = 2048


# Backend attribution: filled in by main() after the liveness probe and
# stamped on EVERY emitted row plus the final parsed JSON line, so the
# numbers stay attributable even when only the output tail is stored.
BACKEND = {"backend": "unknown", "cpu_fallback": False}

# Steady-state per-cycle transport from the e2e runs' flight-recorder
# traces (filled by _run_e2e, gated by bench_transport_bytes).
_TRANSPORT_STATS: dict = {}

# Transport rangespec (ISSUE 11 acceptance): the decision-only fetch
# must stay under 120 KB/cycle at the north-star head shape — >5x
# under the r05 dense fetch. Absolute bytes are transport-framing
# dependent, so the spec is backend-stamped and cross-backend runs
# refuse per the honesty policy; the >5x packed-vs-dense RATIO is pure
# byte math and asserts on every backend. The upload bound is a LOOSE
# order-of-regression guard only: the progressive-fill scenario
# mass-churns the arena (a full head wave of fresh rows every cycle
# exceeds the 512-row scatter bucket, so the twin re-uploads
# wholesale by design) — the bound catches an unbounded-twin or
# per-cycle-state-re-upload regression, not the churn-proportional
# scatter cost.
TRANSPORT_RANGESPEC_BACKEND = "cpu"
TRANSPORT_MAX_FETCH_BYTES_PER_CYCLE = 120_000
TRANSPORT_MAX_UPLOAD_BYTES_PER_CYCLE = 32_000_000
TRANSPORT_MIN_DENSE_FETCH_RATIO = 5.0


def log(obj):
    print(json.dumps({**obj, **BACKEND}), file=sys.stderr)


def p50(times):
    times = sorted(times)
    return times[len(times) // 2]


def p99(times):
    times = sorted(times)
    return times[min(len(times) - 1, int(len(times) * 0.99))]


# -- object-model scenario builders (self-contained) ----------------------

def make_flavor(name):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import ObjectMeta
    return api.ResourceFlavor(metadata=ObjectMeta(name=name, uid=f"rf-{name}"))


def make_cq(name, cohort, flavors, nominal_units, preemption=None):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import LabelSelector, ObjectMeta
    cq = api.ClusterQueue(metadata=ObjectMeta(name=name, uid=f"cq-{name}"))
    cq.spec.namespace_selector = LabelSelector()
    cq.spec.cohort = cohort
    if preemption is not None:
        cq.spec.preemption = preemption
    fqs = []
    for f in flavors:
        fqs.append(api.FlavorQuotas(name=f, resources=[
            api.ResourceQuota(name="cpu", nominal_quota=nominal_units * 1000),
            api.ResourceQuota(name="memory", nominal_quota=nominal_units << 30),
        ]))
    cq.spec.resource_groups.append(api.ResourceGroup(
        covered_resources=["cpu", "memory"], flavors=fqs))
    return cq


def make_lq(name, cq):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import ObjectMeta
    lq = api.LocalQueue(metadata=ObjectMeta(name=name, namespace="default",
                                            uid=f"lq-{name}"))
    lq.spec.cluster_queue = cq
    return lq


def make_workload(name, queue, cpu_units, priority=0, creation=0.0):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.corev1 import Container, PodSpec, PodTemplateSpec
    from kueue_tpu.api.meta import ObjectMeta
    wl = api.Workload(metadata=ObjectMeta(
        name=name, namespace="default", uid=f"wl-{name}",
        creation_timestamp=creation))
    wl.spec.queue_name = queue
    wl.spec.priority = priority
    spec = PodSpec(containers=[Container(
        name="c", requests={"cpu": cpu_units * 1000, "memory": cpu_units << 30})])
    wl.spec.pod_sets.append(api.PodSet(
        name="main", count=1, template=PodTemplateSpec(spec=spec)))
    return wl


class BenchClient:
    """Minimal SchedulerClient: counts admissions, no store."""

    def __init__(self):
        self.admitted = 0
        self.evicted = 0
        self.new_applied = []  # admission writes since last drain

    def namespace_labels(self, namespace):
        return {}

    def limit_ranges(self, namespace):
        return []

    def apply_admission(self, wl):
        from kueue_tpu.core import workload as wlpkg
        if wlpkg.is_evicted(wl):
            self.evicted += 1
        else:
            self.admitted += 1
            self.new_applied.append(wl)

    def drain_applied(self):
        out, self.new_applied = self.new_applied, []
        return out

    def patch_not_admitted(self, wl):
        pass

    def event(self, wl, event_type, reason, message):
        pass


def build_env(num_cqs, num_cohorts, flavors, nominal_units, solver=None,
              preemption=None, fair_sharing=False, pipeline=False,
              routed=False):
    from kueue_tpu.api.meta import FakeClock
    from kueue_tpu.cache import Cache
    from kueue_tpu.queue import Manager
    from kueue_tpu.scheduler.scheduler import Scheduler
    clock = FakeClock(1000.0)
    cache = Cache()
    queues = Manager(clock=clock)
    client = BenchClient()
    sched = Scheduler(queues, cache, client, clock=clock, solver=solver,
                      solver_min_heads=0, fair_sharing_enabled=fair_sharing)
    sched.pipeline_enabled = pipeline
    if pipeline:
        # the PRODUCTION config (manager wiring): dispatch depth 2 —
        # the e2e/transport rows must exercise the depth the default
        # deployment runs (SolverConfig.pipeline_depth)
        from kueue_tpu.config import SolverConfig
        sched.pipeline_depth = SolverConfig().pipeline_depth
    if routed:
        sched.solver_routing = "adaptive"
    for f in flavors:
        cache.add_or_update_resource_flavor(make_flavor(f))
    for i in range(num_cqs):
        cq = make_cq(f"cq{i}", f"cohort-{i % num_cohorts}", flavors,
                     nominal_units, preemption=preemption)
        cache.add_cluster_queue(cq)
        queues.add_cluster_queue(cq)
        queues.add_local_queue(make_lq(f"lq{i}", f"cq{i}"))
    return sched, cache, queues, client, clock


# -- benchmarks -----------------------------------------------------------

def bench_kernel():
    import jax
    import jax.numpy as jnp

    from kueue_tpu.solver.kernel import (
        max_rank_bound, solve_cycle, solve_cycle_fused)
    from kueue_tpu.solver.synth import synth_solver_inputs

    topo, usage, cohort_usage, wl = synth_solver_inputs(
        num_cqs=NUM_CQS, num_cohorts=NUM_COHORTS, num_flavors=NUM_FLAVORS,
        num_resources=NUM_RESOURCES, num_workloads=HEADS, seed=42)
    topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}
    args = (jnp.asarray(usage), jnp.asarray(cohort_usage),
            jnp.asarray(wl["requests"]), jnp.asarray(wl["podset_active"]),
            jnp.asarray(wl["wl_cq"]), jnp.asarray(wl["priority"]),
            jnp.asarray(wl["timestamp"]), jnp.asarray(wl["eligible"]),
            jnp.asarray(wl["solvable"]))

    from functools import partial

    from kueue_tpu.solver.kernel import solve_cycle_fused_impl, solve_cycle_impl

    max_rank = max_rank_bound(wl["wl_cq"], topo["cq_cohort"],
                              topo["cohort_root"])

    # measure the tunnel/dispatch round-trip floor with a trivial op
    triv = jax.jit(lambda a: a + 1)
    import numpy as np
    int(np.asarray(triv(jnp.ones(8, jnp.int32))).sum())
    t_rt = []
    for _ in range(5):
        t0 = time.perf_counter()
        int(np.asarray(triv(jnp.ones(8, jnp.int32))).sum())
        t_rt.append(time.perf_counter() - t0)
    rt_ms = p50(t_rt) * 1e3

    def run_global():
        return solve_cycle(topo_dev, *args, num_podsets=1)

    def run_cp():
        return solve_cycle_fused(topo_dev, *args, num_podsets=1,
                                 max_rank=max_rank)

    def sync(out):
        return int(np.asarray(out["admitted"]).sum())

    admitted = sync(run_global())
    t_global = []
    for _ in range(8):
        t0 = time.perf_counter()
        sync(run_global())
        t_global.append(time.perf_counter() - t0)

    admitted_cp = sync(run_cp())
    t_cp = []
    for _ in range(8):
        t0 = time.perf_counter()
        sync(run_cp())
        t_cp.append(time.perf_counter() - t0)
    assert admitted == admitted_cp, (admitted, admitted_cp)

    # device-compute isolation: run N chained solves in ONE dispatch (an
    # output->input data dependence stops XLA hoisting), so the
    # per-cycle device time excludes the host round-trip entirely
    def chained(impl_kwargs, impl, n):
        def body(i, prio):
            out = impl(topo_dev, *args[:5], prio, *args[6:], **impl_kwargs)
            return prio + out["admitted"].astype(jnp.int64)
        return jax.lax.fori_loop(0, n, body, args[5])

    def device_per_cycle(impl, **impl_kwargs):
        fn = jax.jit(partial(chained, impl_kwargs, impl), static_argnums=0)
        ts = {}
        for n in (1, 17):
            int(np.asarray(fn(n)).sum())  # compile + warm
            t0 = time.perf_counter()
            int(np.asarray(fn(n)).sum())
            ts[n] = time.perf_counter() - t0
        return max(0.0, (ts[17] - ts[1]) / 16)

    dev_global = device_per_cycle(solve_cycle_impl, num_podsets=1)
    dev_fused = device_per_cycle(solve_cycle_fused_impl, num_podsets=1,
                                 max_rank=max_rank)

    log({"bench": "device_round_trip_floor", "p50_ms": round(rt_ms, 1)})
    log({"bench": "kernel_global_scan", "p50_ms": round(p50(t_global) * 1e3, 2),
         "device_only_ms": round(dev_global * 1e3, 3),
         "admitted_per_cycle": admitted})
    log({"bench": "kernel_fused_cohort_parallel", "max_rank": max_rank,
         "p50_ms": round(p50(t_cp) * 1e3, 2),
         "device_only_ms": round(dev_fused * 1e3, 3),
         "admitted_per_cycle": admitted_cp,
         "device_speedup_vs_global": round(dev_global / max(dev_fused, 1e-9), 1)})
    return p50(t_cp), admitted_cp


def _run_e2e(solver, waves, cpu_units, label, pipeline=False,
             routed=False):
    """One end-to-end run: `waves` waves of one-workload-per-CQ, full
    Scheduler.schedule cycles (heads + snapshot + nominate/solve + admit +
    requeue). Wave 0 is warmup (jit compile); waves 1.. are timed.
    The solver path runs the PRODUCTION config: device-resident state +
    pipelined dispatch (decisions land one cycle later; the drain cycles
    at the end are included in the wall time, so throughput is honest)
    + the adaptive engine router when routed=True — on a backend where
    the device engine loses, the routed number converges to CPU parity
    instead of paying the pinned-device tax.
    Returns (cycle times, admitted count over timed cycles)."""
    flavors = [f"f{i}" for i in range(NUM_FLAVORS)]
    sched, cache, queues, client, clock = build_env(
        NUM_CQS, NUM_COHORTS, flavors, nominal_units=40, solver=solver,
        pipeline=pipeline, routed=routed)
    n = 0
    for wave in range(waves):
        for i in range(NUM_CQS):
            wl = make_workload(f"w{wave}-{i}", f"lq{i}", cpu_units=cpu_units,
                               priority=n % 5, creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1
    # Warmup compiles the bucketed shapes; in pipelined mode the first
    # collect (one cycle after the first dispatch) pays the compile, so
    # warm two cycles there. Routed runs warm five: the dispatch-only
    # first cycle records no routing sample, and the router's mandatory
    # per-engine samples (2 device + 2 cpu) must all land before the
    # clock, not inside the timed p50.
    warmup = (5 if routed else 2) if pipeline else 1
    for _ in range(warmup):
        sched.schedule(timeout=0)
    before = client.admitted
    times = []
    for _ in range(waves - warmup):
        t0 = time.perf_counter()
        sched.schedule(timeout=0)
        times.append(time.perf_counter() - t0)
    # drain the pipeline: admissions of the last in-flight cycle count
    while sched._inflight is not None:
        t0 = time.perf_counter()
        sched.schedule(timeout=0)
        times.append(time.perf_counter() - t0)
    admitted = client.admitted - before
    assert admitted > 0, label
    if solver is not None:
        row = {"bench": f"{label}_payload",
               "upload_bytes": solver.last_upload_bytes,
               "fetch_bytes": solver.last_fetch_bytes}
        # Per-cycle transport from the flight recorder (decision-only
        # fetch): device-routed cycles' wire bytes per round trip —
        # bench_transport_bytes gates the steady-state numbers.
        dev_traces = [t for t in sched.recorder.traces()
                      if t.route.startswith("device") and t.collects]
        if dev_traces:
            fpc = sorted(t.fetch_bytes / t.collects for t in dev_traces)
            upc = sorted(t.upload_bytes / max(t.dispatches, 1)
                         for t in dev_traces)
            row["fetch_bytes_per_cycle_p50"] = int(p50(fpc))
            row["upload_bytes_per_cycle_p50"] = int(p50(upc))
            topo = (solver._topo_cache[0]
                    if solver._topo_cache is not None else None)
            stats = {
                "fetch_p50": p50(fpc), "upload_p50": p50(upc),
                "device_cycles": len(dev_traces),
                "num_resources": (topo.nominal.shape[2]
                                  if topo is not None else None),
                "max_podsets": solver.max_podsets,
            }
            if topo is not None:
                # Packed-vs-dense ratio PER TRACE, each at its own
                # bucketed batch width — a run whose median cycle pops
                # fewer heads than the headline bucket must not let a
                # dense-fetch regression hide behind a wide denominator
                # (bench_transport_bytes gates the p50 of these).
                from kueue_tpu.solver import encode as _enc
                from kueue_tpu.solver.kernel import dense_decision_nbytes
                R = topo.nominal.shape[2]
                ratios = sorted(
                    dense_decision_nbytes(
                        _enc._bucket(max(1, t.heads)),
                        solver.max_podsets, R)
                    / max(t.fetch_bytes / t.collects, 1.0)
                    for t in dev_traces)
                stats["dense_fetch_ratio_p50"] = p50(ratios)
                row["dense_fetch_ratio_p50"] = round(p50(ratios), 2)
            _TRANSPORT_STATS[label] = stats
        log(row)
    builds = cache.snapshot_build_s
    if builds:
        # snapshot-build cost as its own metric: p50/p99 per full
        # cache.snapshot() call plus which path served each one
        log({"bench": f"{label}_snapshot_build",
             "p50_ms": round(p50(builds) * 1e3, 3),
             "p99_ms": round(p99(builds) * 1e3, 3),
             "counts": dict(cache.snapshot_stats)})
    return times, admitted, client.admitted


def bench_snapshot_incremental(workloads_per_cq=8, deltas_per_cycle=8,
                               iters=12):
    """Snapshot maintenance at the flagship shape (2048 CQs x 32
    flavors, workloads_per_cq admitted workloads each): the per-cycle
    full deep clone (the pre-incremental cost, still the fallback path)
    vs the journal-replay advance with a handful of workload deltas per
    cycle (steady state). Pure host-side work — no device involved."""
    flavors = [f"f{i}" for i in range(NUM_FLAVORS)]
    sched, cache, queues, client, clock = build_env(
        NUM_CQS, NUM_COHORTS, flavors, nominal_units=400)
    for i in range(NUM_CQS):
        for v in range(workloads_per_cq):
            _admit_victim(cache, f"w{i}-{v}", f"lq{i}", f"cq{i}",
                          100, 0, float(v))
    cache.snapshot()  # establish the maintained snapshot (full build)
    t_full, t_incr = [], []
    churn = []
    n = 0
    for it in range(iters):
        # steady-state deltas: a few admissions/completions per cycle
        for wl in churn:
            cache.delete_workload(wl)
        churn = []
        for d in range(deltas_per_cycle):
            churn.append(_admit_victim(
                cache, f"churn{it}-{d}", f"lq{n % NUM_CQS}",
                f"cq{n % NUM_CQS}", 50, 0, 1000.0 + n))
            n += 1
        t0 = time.perf_counter()
        cache.snapshot()
        t_incr.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        cache._build_snapshot()
        t_full.append(time.perf_counter() - t0)
    assert cache.snapshot_stats["incremental"] >= iters, cache.snapshot_stats
    speedup = p50(t_full) / max(p50(t_incr), 1e-9)
    log({"bench": "snapshot_incremental", "cqs": NUM_CQS,
         "flavors": NUM_FLAVORS, "workloads_per_cq": workloads_per_cq,
         "deltas_per_cycle": deltas_per_cycle,
         "full_clone_p50_ms": round(p50(t_full) * 1e3, 2),
         "incremental_p50_ms": round(p50(t_incr) * 1e3, 2),
         "speedup": round(speedup, 1)})
    return speedup


def bench_workload_arena(pending=50_000, heads=HEADS, churn_frac=0.05,
                         iters=10):
    """Per-cycle batch assembly at the north-star 50k-pending x 2048-CQ
    x 32-flavor shape with <=5% of the cycle's heads churning: the
    persistent workload encode arena (O(changed) row re-encodes + one
    vectorized slot gather, solver/arena.py) vs the pre-arena per-head
    reassembly loop (encode_workloads with WARM per-Info row caches —
    its best case). Pure host-side work; every iteration also asserts
    the arena batch is bit-identical to the oracle's."""
    import numpy as np
    from kueue_tpu.core import workload as wlpkg
    from kueue_tpu.solver import encode
    from kueue_tpu.solver.arena import WorkloadArena

    flavors = [f"f{i}" for i in range(NUM_FLAVORS)]
    sched, cache, queues, client, clock = build_env(
        NUM_CQS, NUM_COHORTS, flavors, nominal_units=40)
    snapshot = cache.snapshot()
    topo = encode.encode_topology(snapshot)
    ordering = wlpkg.Ordering()
    P = 4

    def make_info(name, i):
        info = wlpkg.Info(make_workload(name, f"lq{i % NUM_CQS}",
                                        cpu_units=4, priority=i % 5,
                                        creation=float(i)))
        info.cluster_queue = f"cq{i % NUM_CQS}"
        return info

    infos = [make_info(f"w{i}", i) for i in range(pending)]
    arena = WorkloadArena(P)
    arena.begin_cycle(topo)
    # steady state: every pending row encoded once (first sight), and
    # the oracle's per-Info caches warm
    for off in range(0, pending, heads):
        window = infos[off:off + heads]
        arena.assemble(window, snapshot, topo, ordering, P)
        encode.encode_workloads(window, snapshot, topo, ordering=ordering,
                                max_podsets=P)
    churn = max(1, int(heads * churn_frac))
    t_arena, t_fresh = [], []
    n = pending
    # The head set mirrors the north-star cycle: heads() pops one head
    # per CQ, non-admitted heads requeue and return next cycle, so the
    # window is STABLE except for the <=5% that admit (slot freed) and
    # the arrivals that replace them.
    window = infos[:heads]
    for it in range(iters):
        for j in range(churn):
            pos = (it * churn + j) % heads
            arena.note("del", window[pos].key)
            info = make_info(f"w{n}", n)
            n += 1
            window[pos] = info
        t0 = time.perf_counter()
        batch_a, _ = arena.assemble(window, snapshot, topo, ordering, P)
        t_arena.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        batch_f = encode.encode_workloads(window, snapshot, topo,
                                          ordering=ordering, max_podsets=P)
        t_fresh.append(time.perf_counter() - t0)
        for name in ("requests", "podset_active", "wl_cq", "priority",
                     "timestamp", "eligible", "solvable", "start_rank"):
            assert np.array_equal(getattr(batch_a, name),
                                  getattr(batch_f, name)), name
    # min-of-N, like the preemption rows: both are host-only loops, so
    # the minimum is the interference-free cost on a contended machine
    speedup = min(t_fresh) / max(min(t_arena), 1e-9)
    log({"bench": "workload_arena", "pending": pending, "heads": heads,
         "churn_per_cycle": churn, "cqs": NUM_CQS, "flavors": NUM_FLAVORS,
         "fresh_encode_ms": round(min(t_fresh) * 1e3, 2),
         "arena_encode_ms": round(min(t_arena) * 1e3, 2),
         "fresh_encode_p99_ms": round(p99(t_fresh) * 1e3, 2),
         "arena_encode_p99_ms": round(p99(t_arena) * 1e3, 2),
         "speedup": round(speedup, 1)})
    return speedup


def bench_device_fault_recovery(num_cqs=256, num_cohorts=32, burst=3,
                                max_cycles=24):
    """Device-fault containment (kueue_tpu/resilience): a scripted burst
    of `burst` consecutive dispatch faults must trip the breaker, route
    the outage cycles as cpu-breaker (admissions keep flowing on the CPU
    fallback), and recover the device route via half-open probes within
    a BOUNDED number of cycles. Also pins the zero-cost-when-disabled
    contract: the measured per-cycle cost of the disabled injection
    sites must be <=1% of the fault-free cycle p50."""
    import timeit

    from kueue_tpu.resilience import faultinject
    from kueue_tpu.resilience.breaker import CLOSED, CircuitBreaker
    from kueue_tpu.resilience.faultinject import RAISE, FaultInjector
    from kueue_tpu.solver import BatchSolver

    flavors = ["f0"]
    sched, cache, queues, client, clock = build_env(
        num_cqs, num_cohorts, flavors, nominal_units=400,
        solver=BatchSolver())
    sched.breaker = CircuitBreaker(threshold=2, backoff_base_s=2.0,
                                   backoff_max_s=8.0, jitter=0.0)
    n = 0

    def submit_wave():
        nonlocal n
        for i in range(num_cqs):
            wl = make_workload(f"w{n}", f"lq{i}", cpu_units=2,
                               creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1

    def cycle():
        sched.schedule(timeout=0)
        clock.advance(1.0)

    for _ in range(2):  # warm: compile the shape buckets
        submit_wave()
        cycle()
    # fault-free cycle p50 (the overhead reference)
    times = []
    for _ in range(4):
        submit_wave()
        t0 = time.perf_counter()
        cycle()
        times.append(time.perf_counter() - t0)
    clean_p50 = p50(times)

    # Disabled-path overhead: the hot sites are a module-global load +
    # compare each; ~4 fire per cycle (dispatch, collect, scatter,
    # replay). Measured directly so the assertion is noise-free.
    per_call_s = timeit.timeit(
        lambda: faultinject.site(faultinject.SITE_DISPATCH),
        number=200_000) / 200_000
    overhead_pct = 100.0 * (4 * per_call_s) / max(clean_p50, 1e-9)
    assert overhead_pct <= 1.0, (overhead_pct, clean_p50)

    # Scripted fault burst: consecutive dispatch raises trip the breaker
    # (threshold 2); the tail of the burst fails the first half-open
    # probe, so recovery also exercises the doubled backoff.
    injector = FaultInjector(
        {faultinject.SITE_DISPATCH: {i: RAISE for i in range(burst)}})
    admitted_before = client.admitted
    faultinject.install(injector)
    recovery_cycles = -1
    try:
        for c in range(max_cycles):
            submit_wave()
            cycle()
            if sched.breaker.recoveries:
                recovery_cycles = sched.breaker.last_recovery_cycles
                break
    finally:
        faultinject.uninstall()
    assert recovery_cycles >= 0, "breaker did not recover within the bound"
    assert sched.breaker.state == CLOSED
    assert sched.breaker.trips >= 1
    assert sched.cycle_counts.get("cpu-breaker", 0) >= 1
    # the outage never stopped admissions: every burst cycle's wave
    # admitted through the CPU fallback / cpu-breaker route
    assert client.admitted > admitted_before

    log({"bench": "device_fault_recovery", "cqs": num_cqs, "burst": burst,
         "breaker_trips": sched.breaker.trips,
         "cpu_breaker_cycles": sched.cycle_counts.get("cpu-breaker", 0),
         "dispatch_timeouts": sched.solver.counters["dispatch_timeouts"],
         "recovery_cycles": recovery_cycles,
         "clean_cycle_p50_ms": round(clean_p50 * 1e3, 2),
         "disabled_site_ns": round(per_call_s * 1e9, 1),
         "disabled_overhead_pct": round(overhead_pct, 4)})
    return recovery_cycles


def bench_trace_overhead(num_cqs=256, num_cohorts=32, spans_per_cycle=16):
    """Cycle flight recorder (kueue_tpu/obs): pin the cost contract.
    Disabled, a span/annotate hook is one attribute load + is-None
    compare (like the faultinject sites) — asserted <=1% of a fault-free
    cycle p50; enabled, span capture is a tuple append into the open
    trace — also asserted <=1%. Then runs recorded cycles end-to-end and
    checks the traces are well-formed (route/heads/spans present, ring
    bounded)."""
    import timeit

    from kueue_tpu.obs import FlightRecorder
    from kueue_tpu.solver import BatchSolver

    flavors = ["f0"]
    sched, cache, queues, client, clock = build_env(
        num_cqs, num_cohorts, flavors, nominal_units=400,
        solver=BatchSolver())
    sched.recorder = FlightRecorder(enabled=False)
    n = 0

    def submit_wave():
        nonlocal n
        for i in range(num_cqs):
            wl = make_workload(f"w{n}", f"lq{i}", cpu_units=2,
                               creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1

    def cycle():
        sched.schedule(timeout=0)
        clock.advance(1.0)

    for _ in range(2):  # warm: compile the shape buckets
        submit_wave()
        cycle()
    times = []
    for _ in range(4):
        submit_wave()
        t0 = time.perf_counter()
        cycle()
        times.append(time.perf_counter() - t0)
    clean_p50 = p50(times)

    # Disabled per-hook cost: recorder present, no open trace.
    rec_off = sched.recorder
    per_off_s = timeit.timeit(
        lambda: rec_off.span("encode", 0.0, 0.0),
        number=200_000) / 200_000
    off_pct = 100.0 * (spans_per_cycle * per_off_s) / max(clean_p50, 1e-9)
    assert off_pct <= 1.0, (off_pct, clean_p50)

    # Enabled per-span cost: an open trace absorbing appends.
    rec_on = FlightRecorder(capacity=64)
    rec_on.begin_cycle(0)
    per_on_s = timeit.timeit(
        lambda: rec_on.span("encode", 0.0, 0.0),
        number=200_000) / 200_000
    on_pct = 100.0 * (spans_per_cycle * per_on_s) / max(clean_p50, 1e-9)
    assert on_pct <= 1.0, (on_pct, clean_p50)

    # Recorded cycles end-to-end: the scheduler late-binds the swapped
    # recorder to the solver and every cycle yields a sealed trace.
    sched.recorder = FlightRecorder(capacity=8)
    for _ in range(12):
        submit_wave()
        cycle()
    traces = sched.recorder.traces()
    assert traces and len(traces) <= 8, len(traces)
    assert all(t.route and t.heads >= 0 and t.spans for t in traces)

    log({"bench": "trace_overhead", "cqs": num_cqs,
         "clean_cycle_p50_ms": round(clean_p50 * 1e3, 2),
         "disabled_span_ns": round(per_off_s * 1e9, 1),
         "enabled_span_ns": round(per_on_s * 1e9, 1),
         "disabled_overhead_pct": round(off_pct, 4),
         "enabled_overhead_pct": round(on_pct, 4),
         "traces_recorded": sched.recorder.cycles_recorded})
    return off_pct


def bench_journey_overhead(num_cqs=256, num_cohorts=32):
    """Workload journey ledger (kueue_tpu/obs/journey.py): pin the cost
    contract, mirroring trace_overhead. Disabled, every scheduler hook
    is one attribute load + is-None compare (scheduler.journeys is
    None) — asserted <=1% of a fault-free cycle at one hook per entry;
    enabled, a hook is a span append under the ledger lock — also
    asserted <=1% at one hook per head. Then runs ledgered cycles
    end-to-end and checks the journeys are well-formed (sealed on
    admission, LRU bounded, zero retained after close)."""
    import timeit

    from kueue_tpu.metrics import Registry
    from kueue_tpu.obs.journey import JourneyLedger
    from kueue_tpu.solver import BatchSolver

    flavors = ["f0"]
    sched, cache, queues, client, clock = build_env(
        num_cqs, num_cohorts, flavors, nominal_units=400,
        solver=BatchSolver())
    n = 0

    def submit_wave():
        nonlocal n
        for i in range(num_cqs):
            wl = make_workload(f"w{n}", f"lq{i}", cpu_units=2,
                               creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1

    def cycle():
        sched.schedule(timeout=0)
        clock.advance(1.0)

    for _ in range(2):  # warm: compile the shape buckets
        submit_wave()
        cycle()
    times = []
    for _ in range(4):
        submit_wave()
        t0 = time.perf_counter()
        cycle()
        times.append(time.perf_counter() - t0)
    clean_p50 = p50(times)

    # Disabled per-hook cost: the exact expression every hook site
    # evaluates when no ledger is wired.
    per_off_s = timeit.timeit(
        lambda: sched.journeys is None, number=200_000) / 200_000
    # One hook per entry per cycle (requeue_and_update / admit).
    off_pct = 100.0 * (num_cqs * per_off_s) / max(clean_p50, 1e-9)
    assert off_pct <= 1.0, (off_pct, clean_p50)

    # Enabled per-hook cost: a requeued-span append on a live ledger.
    led = JourneyLedger(capacity=4096, metrics=Registry(), clock=clock,
                        generation_source=cache.generation_token)
    led.begin_cycle(1, cache.generation_token())
    from kueue_tpu.core import workload as wlpkg
    from kueue_tpu.queue import RequeueReason
    sample_info = wlpkg.Info(make_workload("bench-probe", "lq0",
                                           cpu_units=2))
    sample_info.cluster_queue = "cq0"
    per_on_s = timeit.timeit(
        lambda: led.requeued(sample_info, "nominated",
                             RequeueReason.GENERIC),
        number=50_000) / 50_000
    on_pct = 100.0 * (num_cqs * per_on_s) / max(clean_p50, 1e-9)
    assert on_pct <= 1.0, (on_pct, clean_p50)

    # Ledgered cycles end-to-end: journeys seal on admission and the
    # ledger stays bounded + leak-free.
    led2 = JourneyLedger(capacity=128, metrics=Registry(), clock=clock,
                         generation_source=cache.generation_token)
    queues.add_journey_listener(led2.note_queue_delta)
    sched.journeys = led2
    for _ in range(6):
        submit_wave()
        cycle()
    st = led2.status()
    assert st["completed"] > 0, st
    assert st["active"] <= 128, st
    # /metrics and the ledger share one producer: histogram count ==
    # sealed journeys (the reconcile-by-construction satellite).
    hist_count = sum(s[2] for s in
                     led2.metrics.admission_wait_time.series.values())
    assert hist_count == st["completed"], (hist_count, st["completed"])
    led2.close()
    assert led2.retained == 0
    sched.journeys = None

    log({"bench": "journey_overhead", "cqs": num_cqs,
         "clean_cycle_p50_ms": round(clean_p50 * 1e3, 2),
         "disabled_hook_ns": round(per_off_s * 1e9, 1),
         "enabled_hook_ns": round(per_on_s * 1e9, 1),
         "disabled_overhead_pct": round(off_pct, 4),
         "enabled_overhead_pct": round(on_pct, 4),
         "journeys_completed": st["completed"],
         "lru_evictions": st["lru_evictions"]})
    return off_pct


def bench_overload_shed(num_cqs=256, num_cohorts=32, backlog_waves=10,
                        storm_cycles=24, shed_heads=32, survival_heads=8):
    """Bounded-cycle admission (kueue_tpu/resilience/degrade.py): a
    synthetic overload storm — a deep pre-submitted backlog whose full
    cycles blow the configured budget — must walk the ladder into
    shed/survival, and once there the cycle p99 must stay within
    budget x safety factor. Post-detection: the ladder can only see a
    cycle's spend at that cycle's END, so the storm's first (normal-
    state) cycle is the detection cost and is reported separately, not
    asserted against the budget. Also pins: admissions keep flowing
    while shedding, the ladder recovers to normal once load subsides,
    and the IDLE ladder (enabled, normal, no overload) costs <=1% of a
    cycle."""
    import timeit

    from kueue_tpu.resilience.degrade import NORMAL, DegradationLadder

    flavors = ["f0"]
    sched, cache, queues, client, clock = build_env(
        num_cqs, num_cohorts, flavors, nominal_units=100_000)
    n = 0

    def submit_wave(cqs=num_cqs):
        nonlocal n
        for i in range(cqs):
            wl = make_workload(f"w{n}", f"lq{i}", cpu_units=2,
                               creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1

    def cycle():
        t0 = time.perf_counter()
        sched.schedule(timeout=0)
        dt = time.perf_counter() - t0
        clock.advance(1.0)
        return dt

    # Calibrate: a full-width cycle (the storm shape) vs a shed-width
    # cycle. The budget sits well above the shed shape and below the
    # full shape, so the storm overloads it and shedding escapes it.
    for _ in range(2):  # warm
        submit_wave()
        cycle()
    full_times = []
    for _ in range(4):
        submit_wave()
        full_times.append(cycle())
    full_p50 = p50(full_times)
    capped_times = []
    for _ in range(4):
        submit_wave(shed_heads)
        capped_times.append(cycle())
    capped_p50 = p50(capped_times)
    budget = capped_p50 * 3.0
    assert full_p50 > budget, (
        "overload premise failed: full cycle "
        f"{full_p50 * 1e3:.2f}ms <= budget {budget * 1e3:.2f}ms")

    # Idle-ladder overhead: enabled, normal state, healthy cycles — the
    # per-cycle cost is the head-cap check + one EWMA observation.
    idle = DegradationLadder(budget_s=60.0)
    idle.observe_cycle(0.001, backlog=5)
    per_idle_s = timeit.timeit(
        lambda: (idle.head_cap(), idle.defer_preemption,
                 idle.observe_cycle(0.001, backlog=5)),
        number=200_000) / 200_000
    idle_pct = 100.0 * per_idle_s / max(capped_p50, 1e-9)
    assert idle_pct <= 1.0, (idle_pct, capped_p50)

    # The storm: a deep backlog, every full cycle over budget.
    sched.ladder = DegradationLadder(
        budget_s=budget, shed_heads=shed_heads,
        survival_heads=survival_heads, escalate_after=1,
        recovery_cycles=3, ewma_alpha=1.0)
    for _ in range(backlog_waves):
        submit_wave()
    admitted_before = client.admitted
    storm_times = []   # (seconds, ladder rung the cycle RAN under)
    for _ in range(storm_cycles):
        dt = cycle()
        storm_times.append((dt, sched._cycle_degraded))
    degraded = [t for t, rung in storm_times if rung != NORMAL]
    detection = [t for t, rung in storm_times if rung == NORMAL]
    assert degraded, "the ladder never engaged under the storm"
    shed_p99 = p99(degraded)
    safety = 2.0
    assert shed_p99 <= budget * safety, (
        f"shed cycle p99 {shed_p99 * 1e3:.2f}ms exceeded budget "
        f"{budget * 1e3:.2f}ms x {safety}")
    # load shedding bounds latency, it does not stop admissions
    assert client.admitted > admitted_before
    assert sched.shed_heads_requeued > 0

    # Load subsides: trickled small waves keep the ladder observing;
    # it must walk back to normal within the hysteresis bound.
    recovery_cycles = -1
    for c in range(24):
        submit_wave(survival_heads)
        cycle()
        if sched.ladder.state == NORMAL:
            recovery_cycles = c + 1
            break
    assert recovery_cycles > 0, "ladder did not recover after the storm"

    log({"bench": "overload_shed", "cqs": num_cqs,
         "budget_ms": round(budget * 1e3, 2),
         "full_cycle_p50_ms": round(full_p50 * 1e3, 2),
         "capped_cycle_p50_ms": round(capped_p50 * 1e3, 2),
         "storm_cycles": storm_cycles,
         "detection_cycles": len(detection),
         "detection_p50_ms": round(p50(detection) * 1e3, 2) if detection
         else None,
         "shed_cycle_p99_ms": round(shed_p99 * 1e3, 2),
         "budget_x_safety_ms": round(budget * safety * 1e3, 2),
         "cycles_shed": sched.ladder.cycles_shed,
         "escalations": sched.ladder.escalations,
         "shed_heads_requeued": sched.shed_heads_requeued,
         "recovery_cycles": recovery_cycles,
         "idle_ladder_ns": round(per_idle_s * 1e9, 1),
         "idle_overhead_pct": round(idle_pct, 4)})
    return shed_p99


# The scenario_slo row's rangespec bounds (ISSUE 8 acceptance): the two
# SURVEY §5 failure scenarios — the waitForPodsReady requeue flood and
# the MultiKueue worker-cluster loss — must hold their SLO gates
# (bounded per-class p99 time-to-admission, ladder recovery within the
# cycle budget, zero starvation, plus the scenario invariants: jitter
# de-sync, no double dispatch, orphan GC). All gates run in VIRTUAL
# time so they are deterministic per (seed, scale); the row is still
# backend-stamped like every other (perf.checker.refuse_cross_backend
# policy applies if a future spec bounds wall behavior).
#
# The scenarios enforce their own (equal-or-tighter) gates via res.ok;
# this rangespec is the BENCH-SIDE pin, asserted against the observed
# values so the artifact witnesses the bounds even if a scenario's
# internal spec is later loosened. Keep the numbers in sync with
# run_requeue_flood / run_cluster_loss when retuning either.
SCENARIO_SLO_RANGESPEC = {
    "requeue_flood": {"max_ladder_recovery_cycles": 8,
                      "max_requeue_amplification": 4.0,
                      "min_requeue_at_distinct_frac": 0.7},
    "cluster_loss": {"max_requeue_amplification": 3.0,
                     "max_double_dispatched": 0,
                     "max_unplaced_admitted": 0},
}


def bench_scenario_slo(seed=0, scale="smoke"):
    """Production-realism failure scenarios (sim/scenarios.py +
    sim/SCENARIOS.md) as an in-process gate: run the requeue-flood and
    cluster-loss scenarios end-to-end through the full KueueManager and
    assert every SLO gate green. tests/test_scenarios.py owns the full
    six-scenario sweep; this row pins the two failure modes the bench
    artifact must witness every round."""
    from kueue_tpu.sim.scenarios import run_scenario

    results = {}
    for name in ("requeue_flood", "cluster_loss"):
        res = run_scenario(name, seed=seed, scale=scale)
        assert res.ok, (name, res.violations)
        results[name] = res

    flood = results["requeue_flood"]
    spec = SCENARIO_SLO_RANGESPEC["requeue_flood"]
    assert flood.ladder_recovery_cycles is not None \
        and flood.ladder_recovery_cycles <= spec["max_ladder_recovery_cycles"], \
        flood.ladder_recovery_cycles
    assert flood.requeue_amplification <= spec["max_requeue_amplification"], \
        flood.requeue_amplification
    distinct = flood.counters["requeue_at_distinct"]
    total = flood.counters["requeue_ats"]
    # same formula as run_requeue_flood's internal de-sync gate
    assert total and distinct >= max(
        2, int(spec["min_requeue_at_distinct_frac"] * total)), (distinct, total)

    loss = results["cluster_loss"]
    spec = SCENARIO_SLO_RANGESPEC["cluster_loss"]
    assert loss.requeue_amplification <= spec["max_requeue_amplification"], \
        loss.requeue_amplification
    assert loss.counters["double_dispatched"] \
        <= spec["max_double_dispatched"], loss.counters
    assert loss.counters["unplaced_admitted"] \
        <= spec["max_unplaced_admitted"], loss.counters
    # only gate GC when the loss hook actually minted an orphan (a
    # seed/scale with nothing reserving on w1 at loss time has no
    # candidate; the scenario reports that honestly instead of red)
    assert loss.counters["orphan_collected"] \
        or not loss.counters["orphan_candidate"], loss.counters

    log({"bench": "scenario_slo", "seed": seed, "scale": scale,
         "rangespec": {k: dict(v) for k, v in SCENARIO_SLO_RANGESPEC.items()},
         "requeue_flood": {
             "cycles": flood.cycles,
             "admitted": flood.admitted,
             "evictions": flood.evictions,
             "requeue_amplification": round(flood.requeue_amplification, 3),
             "ladder_recovery_cycles": flood.ladder_recovery_cycles,
             "requeue_at_distinct": distinct,
             "requeue_at_spread_s": flood.counters["requeue_at_spread_s"],
             "class_p99_tta_s": {k: round(v, 1)
                                 for k, v in flood.class_p99_tta_s.items()}},
         "cluster_loss": {
             "cycles": loss.cycles,
             "admitted": loss.admitted,
             "relocated": loss.counters["relocated"],
             "double_dispatched": loss.counters["double_dispatched"],
             "orphan_collected": loss.counters["orphan_collected"],
             "requeue_amplification": round(loss.requeue_amplification, 3),
             "class_p99_tta_s": {k: round(v, 1)
                                 for k, v in loss.class_p99_tta_s.items()}}})
    return all(r.ok for r in results.values())


# The speculative_pipeline row's rangespec bound (ISSUE 6 acceptance):
# coverage of the overlapped solve on steady-state traffic. Evaluated
# IN-PROCESS on the current backend only — the row is backend-stamped
# like every other, and cross-round comparison across backends is
# refused by policy (perf.checker.refuse_cross_backend).
SPECULATIVE_PIPELINE_RANGESPEC = {"min_pipelined_hit_rate": 0.9}


def bench_speculative_pipeline(num_cqs=512, num_cohorts=64, cycles=40,
                               churn_at=(15,)):
    """Always-on speculative admission pipeline (scheduler/PIPELINE.md):
    steady-state traffic — every cycle admits a fresh all-fit wave while
    the previous cycle's admissions complete — must keep the solve
    stage overlapped (route device-pipelined) in >90% of device cycles,
    asserted as the rangespec bound above. A scripted mid-run churn
    burst (an in-flight workload updated under the speculation) must
    abort via the generation-token validation and fall back to the
    synchronous path — the abort cost is exactly the sync cycles the
    hit rate already accounts for, and no double admission is possible
    (tests/test_pipeline.py owns the bit-equivalence assertion; this
    row owns the coverage + cost numbers)."""
    from kueue_tpu.solver import BatchSolver

    sched, cache, queues, client, clock = build_env(
        num_cqs, num_cohorts, ["f0"], nominal_units=8,
        solver=BatchSolver(), pipeline=True)
    n = 0

    def submit_wave():
        nonlocal n
        for i in range(num_cqs):
            wl = make_workload(f"w{n}", f"lq{i}", cpu_units=2,
                               creation=float(n))
            queues.add_or_update_workload(wl)
            n += 1

    def run_cycle():
        # steady state: last cycle's admissions complete, freeing their
        # quota through the cache (journal corrections for the solver)
        for wl in client.drain_applied():
            cache.delete_workload(wl)
            queues.queue_associated_inadmissible_workloads_after(wl)
        submit_wave()
        sched.schedule(timeout=0)
        clock.advance(1.0)

    for _ in range(3):  # warm: compiles + the dispatch-only first cycle
        run_cycle()
    counts0 = dict(sched.cycle_counts)
    times = []
    for c in range(cycles):
        t0 = time.perf_counter()
        run_cycle()
        times.append(time.perf_counter() - t0)
        if c in churn_at and sched._inflight is not None:
            # Update a workload that is IN FLIGHT under the speculation:
            # the queue manager's upsert delta bumps its arena slot
            # generation, so the next validation must abort.
            victim = sched._inflight.inflight.plan.batch.infos[0]
            wl = make_workload(victim.obj.metadata.name,
                               victim.obj.spec.queue_name, cpu_units=2,
                               priority=7, creation=float(n))
            queues.add_or_update_workload(wl)
    while sched._inflight is not None:
        t0 = time.perf_counter()
        sched.schedule(timeout=0)
        times.append(time.perf_counter() - t0)
    counts = {k: v - counts0.get(k, 0)
              for k, v in sched.cycle_counts.items()}
    pipelined = counts.get("device-pipelined", 0)
    sync_dev = counts.get("device", 0)
    hit_rate = pipelined / max(pipelined + sync_dev, 1)
    bound = SPECULATIVE_PIPELINE_RANGESPEC["min_pipelined_hit_rate"]
    assert sched.speculation_aborts >= len(churn_at), (
        "scripted churn produced no mis-speculation abort",
        sched.speculation_abort_reasons)
    assert sched.speculation_hits > 0
    assert hit_rate > bound, (
        f"pipelined hit rate {hit_rate:.3f} below the rangespec bound "
        f"{bound} (cycle counts {counts})")
    log({"bench": "speculative_pipeline", "cqs": num_cqs,
         "cycles": pipelined + sync_dev,
         "pipelined_cycles": pipelined, "sync_device_cycles": sync_dev,
         "pipelined_hit_rate": round(hit_rate, 3),
         "rangespec": dict(SPECULATIVE_PIPELINE_RANGESPEC),
         "speculation_hits": sched.speculation_hits,
         "speculation_aborts": sched.speculation_aborts,
         "abort_reasons": dict(sched.speculation_abort_reasons),
         "p50_ms": round(p50(times) * 1e3, 1)})
    return hit_rate


def bench_e2e_progressive():
    """The flagship scenario (BASELINE.json north star): 2048 CQs x 32
    flavors with workloads sized to a full flavor, so cycle N assigns at
    flavor-list depth N — from the empty cluster through a fully loaded
    one. This is the regime the reference's sequential assigner degrades
    in (each entry walks the flavor list past full flavors,
    flavorassigner.go:406-537) while the batched device solve stays flat.
    Measured end-to-end on both paths over the identical schedule."""
    from kueue_tpu.solver import BatchSolver

    out = {}
    for label, mk in (("cpu", lambda: None), ("solver", BatchSolver)):
        # waves = flavor depths + that label's warmup, so BOTH timed
        # windows cover depths 1..32 (aligned shallow/deep sub-windows);
        # waves past depth 32 can't admit, so the total-admissions
        # equality below still holds.
        waves = NUM_FLAVORS + (5 if label == "solver" else 1)
        times, admitted, total_admitted = _run_e2e(
            mk(), waves, cpu_units=40, label=label,
            pipeline=(label == "solver"), routed=(label == "solver"))
        total = sum(times)
        out[label] = (times, admitted, total, total_admitted)
        log({"bench": f"e2e_progressive_fill_{label}",
             "waves": len(times), "admitted": admitted,
             "p50_ms": round(p50(times) * 1e3, 1),
             "shallow_ms": round(p50(times[:8]) * 1e3, 1),
             "deep_ms": round(p50(times[-8:]) * 1e3, 1),
             "wall_s": round(total, 2),
             "admitted_per_sec": round(admitted / total, 1)})
    t_cpu, t_dev = out["cpu"][2], out["solver"][2]
    # Total admissions (incl. warmup) must agree; both labels' timed
    # windows cover the same fill depths (waves are sized per label's
    # warmup above).
    assert out["cpu"][3] == out["solver"][3], (out["cpu"][3], out["solver"][3])
    # throughput on the identical timed workload window
    per_sec_cpu = out["cpu"][1] / t_cpu
    per_sec_dev = out["solver"][1] / t_dev
    speedup = per_sec_dev / per_sec_cpu
    log({"bench": "e2e_progressive_fill", "speedup": round(speedup, 2)})
    # Fused-route floor (ISSUE 11): on a device backend the fully
    # fused single-chip cycle (one dispatch, decision-only fetch,
    # donated uploads) must beat the CPU path end-to-end. cpu_fallback
    # runs refuse the comparison into the witness-debt manifest — the
    # exact gate a future device run must witness.
    from kueue_tpu.perf.checker import (RangeSpec, check_device_speedup,
                                        record_refusal)
    spec = RangeSpec(backend="tpu", min_device_speedup=1.0)
    ok, note = check_device_speedup(speedup, spec, BACKEND)
    if ok is None:
        record_refusal("bench.e2e_progressive_fill", "fused_route_floor",
                       note, spec.backend)
    elif not ok:
        raise AssertionError(note)
    return per_sec_dev, speedup


def bench_transport_bytes():
    """Gate the steady-state per-cycle transport measured by the e2e
    progressive-fill solver run (decision-only fetch + donated arena
    uploads): the p50 device-cycle fetch must sit >5x under the dense
    [W,...] fetch it replaced (byte math — backend-agnostic), and the
    absolute bytes/cycle under the backend-stamped rangespec bounds
    (cross-backend comparison refused into the witness-debt manifest)."""
    from kueue_tpu.perf.checker import (RangeSpec, record_refusal,
                                        refuse_cross_backend)
    from kueue_tpu.solver import encode
    from kueue_tpu.solver.kernel import dense_decision_nbytes
    st = _TRANSPORT_STATS.get("solver")
    if st is None or not st.get("device_cycles"):
        log({"bench": "transport_bytes", "skipped":
             "no device-routed e2e cycles recorded"})
        return
    W = encode._bucket(HEADS)
    P = st["max_podsets"]
    R = st["num_resources"]
    # What the staged fetch shipped per cycle at the headline shape
    # (context only); the RATIO gate uses the per-trace p50 computed
    # at each cycle's OWN bucketed width (_run_e2e) so a dense-fetch
    # regression cannot hide behind a wider denominator, falling back
    # to the headline-width estimate when topology dims were missing.
    dense_fetch = dense_decision_nbytes(W, P, R)
    ratio = st.get("dense_fetch_ratio_p50",
                   dense_fetch / max(st["fetch_p50"], 1.0))
    spec = RangeSpec(
        backend=TRANSPORT_RANGESPEC_BACKEND,
        max_fetch_bytes_per_cycle=TRANSPORT_MAX_FETCH_BYTES_PER_CYCLE,
        max_upload_bytes_per_cycle=TRANSPORT_MAX_UPLOAD_BYTES_PER_CYCLE)
    row = {"bench": "transport_bytes", "heads": HEADS, "batch_width": W,
           "num_podsets": P, "num_resources": R,
           "device_cycles": st["device_cycles"],
           "fetch_bytes_per_cycle_p50": int(st["fetch_p50"]),
           "upload_bytes_per_cycle_p50": int(st["upload_p50"]),
           "dense_fetch_equiv_bytes": dense_fetch,
           "dense_fetch_ratio": round(ratio, 2),
           "rangespec": {
               "backend": spec.backend,
               "max_fetch_bytes_per_cycle":
                   spec.max_fetch_bytes_per_cycle,
               "max_upload_bytes_per_cycle":
                   spec.max_upload_bytes_per_cycle,
               "min_dense_fetch_ratio": TRANSPORT_MIN_DENSE_FETCH_RATIO}}
    # The ratio gate is byte math over this run's own arrays: it holds
    # (or fails) identically on every backend — never refused.
    if ratio <= TRANSPORT_MIN_DENSE_FETCH_RATIO:
        row["rangespec_ok"] = False
        row["rangespec_violation"] = (
            f"packed fetch only {ratio:.2f}x under the dense "
            f"equivalent (floor {TRANSPORT_MIN_DENSE_FETCH_RATIO}x) — "
            f"the decision-only fetch regressed toward dense tensors")
        log(row)
        raise AssertionError(row["rangespec_violation"])
    refusal = refuse_cross_backend(spec, BACKEND)
    if refusal is not None:
        row["rangespec_ok"] = None
        row["rangespec_refused"] = refusal
        record_refusal("bench.transport_bytes", "bytes_per_cycle",
                       refusal, spec.backend)
        log(row)
        return
    violations = []
    if st["fetch_p50"] > spec.max_fetch_bytes_per_cycle:
        violations.append(
            f"fetch p50 {st['fetch_p50']:.0f} bytes/cycle exceeds "
            f"{spec.max_fetch_bytes_per_cycle}")
    if st["upload_p50"] > spec.max_upload_bytes_per_cycle:
        violations.append(
            f"upload p50 {st['upload_p50']:.0f} bytes/cycle exceeds "
            f"{spec.max_upload_bytes_per_cycle}")
    row["rangespec_ok"] = not violations
    if violations:
        row["rangespec_violation"] = "; ".join(violations)
        log(row)
        raise AssertionError(row["rangespec_violation"])
    log(row)


def bench_visibility_storm(pending_waves=25, timed_cycles=10,
                           reader_threads=4, target_qps=240):
    """Snapshot-backed query plane under the north-star admission storm
    (ISSUE 12): 2048 CQs x 32 flavors with ~50k pending workloads, the
    identical storm run twice — no readers (baseline) vs the query
    plane attached with reader threads sustaining a bounded read QPS
    against sealed views while the admission cycles run.

    Gates (the read plane must be FREE for the write plane):
    - HARD: the seal-side publish cost (the only query-plane work on
      the admission cycle's critical path) <= 1% of the baseline cycle
      p50 — microbenched like trace_overhead, so the gate is
      deterministic;
    - HARD: every sampled response carried a generation token whose lag
      vs the live cache never exceeded ONE structural generation (a
      mid-run quota edit makes the gate non-vacuous), and zero snapshot
      handouts leak after the plane closes;
    - in-process rangespec (backend-stamped per the honesty policy):
      measured concurrent p50/p99 admission-cycle overhead <= 1% vs
      the no-readers baseline. Wall-clock A/B on a shared box is
      noise-bound, so a run whose baseline halves drift >3% REFUSES
      the comparison into the witness-debt manifest instead of
      reporting a regression (or a pass) that is really scheduler
      jitter.

    Read capacity (storm QPS) is measured separately with the
    admission loop idle: spinning readers against the last sealed
    view's cached tables — the plane's saturation ceiling, GIL-shared
    with nothing."""
    import threading

    from kueue_tpu.obs.queryplane import QueryPlane
    from kueue_tpu.perf.checker import record_refusal

    flavors = [f"f{i}" for i in range(NUM_FLAVORS)]

    def run_storm(attach_plane):
        # Stationary storm: small workloads against deep quota, so every
        # timed cycle admits a full 2048-head wave off a backlog that
        # stays tens-of-thousands deep — cycle times are comparable
        # across the run (the progressive-fill shape's depth ramp would
        # swamp a 1% A/B bound in systematic drift).
        sched, cache, queues, client, clock = build_env(
            NUM_CQS, NUM_COHORTS, flavors, nominal_units=4000)
        plane = None
        if attach_plane:
            plane = QueryPlane(cache, queues)
            sched.query_plane = plane
        n = 0
        for wave in range(pending_waves):
            for i in range(NUM_CQS):
                wl = make_workload(f"w{wave}-{i}", f"lq{i}", cpu_units=2,
                                   priority=n % 5, creation=float(n))
                queues.add_or_update_workload(wl)
                n += 1
        def run_cycle():
            # Steady state: last cycle's admissions complete (the
            # bench_fair_sharing idiom) so the cache's workload maps —
            # and with them the per-cycle snapshot replay cost — stay
            # stationary; without completions every cycle is slower
            # than the last and an A/B p50 comparison drowns in drift.
            for wl in client.drain_applied():
                cache.delete_workload(wl)
                queues.queue_associated_inadmissible_workloads_after(wl)
            sched.schedule(timeout=0)
            clock.advance(1.0)

        for _ in range(2):  # warmup cycles (cold caches / first snapshot)
            run_cycle()

        stop = threading.Event()
        per_thread = [[] for _ in range(reader_threads)]
        warming = [0]

        # Readers poll a HOT set of queues (a storm is many users
        # watching few queues): the first read of a CQ per sealed view
        # pays its table build on the READER thread, every later read
        # hits the cached immutable table — the amortization the plane
        # exists for. Cold-CQ cost shows up in tables_built and the
        # idle-capacity section instead.
        hot_cqs = 64

        def reader(idx):
            samples = per_thread[idx]
            period = reader_threads / float(target_qps)
            next_t = time.perf_counter() + idx * period / reader_threads
            k = idx
            while not stop.is_set():
                t0 = time.perf_counter()
                view = plane.acquire()
                if view is None:
                    warming[0] += 1
                else:
                    try:
                        plane.pending_cq(view, f"cq{k % hot_cqs}", 20, 0)
                        lag = cache.generation_lag(view.generation)
                        samples.append((time.perf_counter() - t0, lag))
                    finally:
                        plane.release(view)
                k += reader_threads
                next_t += period
                delay = next_t - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)

        threads = []
        if attach_plane:
            threads = [threading.Thread(target=reader, args=(i,),
                                        daemon=True)
                       for i in range(reader_threads)]
            for t in threads:
                t.start()
        import gc
        times = []
        t_run0 = time.perf_counter()
        for c in range(timed_cycles):
            if c == timed_cycles // 2:
                # One structural edit mid-storm (same schedule both
                # runs): the generation token moves, so the staleness
                # gate exercises a real lag window.
                cache.update_cluster_queue(
                    make_cq("cq0", "cohort-0", flavors,
                            nominal_units=4001))
            gc.collect()  # a prior cycle's garbage stays out of this one
            t0 = time.perf_counter()
            run_cycle()
            times.append(time.perf_counter() - t0)
        run_wall = time.perf_counter() - t_run0
        stop.set()
        for t in threads:
            t.join(timeout=10)
        reads = [s for lst in per_thread for s in lst]
        return sched, cache, plane, times, run_wall, reads, warming[0]

    _sched_b, _cache_b, _, base_times, _, _, _ = run_storm(False)
    sched, cache, plane, read_times, run_wall, reads, warming = \
        run_storm(True)

    base_p50, base_p99 = p50(base_times), p99(base_times)
    with_p50, with_p99 = p50(read_times), p99(read_times)
    overhead_p50 = with_p50 / base_p50 - 1.0
    overhead_p99 = with_p99 / base_p99 - 1.0

    # HARD staleness/consistency gates (backend-independent).
    assert reads, "reader storm recorded no samples"
    lat = sorted(s[0] for s in reads)
    max_lag = max(s[1] for s in reads)
    assert max_lag <= 1, (
        f"read staleness {max_lag} structural generations — a sealed "
        f"view may lag only between an edit and the next cycle seal")

    # HARD seal-side cost gate (the admission cycle's share of the
    # query plane): one publish per cycle, microbenched.
    order = [f"default/w0-{i}" for i in range(HEADS)]
    t0 = time.perf_counter()
    n_pub = 50
    for i in range(n_pub):
        plane.publish(10_000 + i, "bench", order, snapshot=None)
    per_publish_s = (time.perf_counter() - t0) / n_pub
    publish_pct = 100.0 * per_publish_s / max(base_p50, 1e-9)
    assert publish_pct <= 1.0, (publish_pct, base_p50)

    # Read capacity with the admission loop idle: the plane's ceiling
    # against CACHED tables (the hot set the storm readers polled —
    # same amortization; cold-table cost is the storm's tables_built
    # counter, snapshotted BEFORE this loop so the row reports the
    # storm's builds, not the bench's own probing).
    storm_tables_built = plane.tables_built
    cap_lat = []
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.5:
        r0 = time.perf_counter()
        view = plane.acquire()
        try:
            plane.pending_cq(view, f"cq{len(cap_lat) % 64}", 20, 0)
        finally:
            plane.release(view)
        cap_lat.append(time.perf_counter() - r0)
    capacity_qps = len(cap_lat) / 0.5

    # Handout hygiene: the plane held the last cycle's snapshot; close
    # must return every handout (the live_handouts leak contract).
    plane.close()
    assert cache.live_handouts == 0, cache.live_handouts

    row = {"bench": "visibility_storm",
           "pending": pending_waves * NUM_CQS, "cqs": NUM_CQS,
           "timed_cycles": timed_cycles,
           "base_cycle_p50_ms": round(base_p50 * 1e3, 1),
           "base_cycle_p99_ms": round(base_p99 * 1e3, 1),
           "readers_cycle_p50_ms": round(with_p50 * 1e3, 1),
           "readers_cycle_p99_ms": round(with_p99 * 1e3, 1),
           "overhead_p50_pct": round(overhead_p50 * 100, 2),
           "overhead_p99_pct": round(overhead_p99 * 100, 2),
           "sustained_read_qps": round(len(reads) / run_wall, 1),
           "read_latency_p50_us": round(p50(lat) * 1e6, 1),
           "read_latency_p99_us": round(p99(lat) * 1e6, 1),
           "read_capacity_qps_idle": round(capacity_qps, 1),
           "capacity_read_p99_us": round(p99(cap_lat) * 1e6, 1),
           "reads": len(reads), "warming_reads": warming,
           "max_token_lag": max_lag,
           "publish_per_cycle_us": round(per_publish_s * 1e6, 1),
           "publish_overhead_pct": round(publish_pct, 4),
           "tables_built": storm_tables_built,
           "rangespec": {"backend": "cpu", "max_overhead_pct": 1.0}}

    # The wall A/B overhead gate: honesty first. The bound was
    # calibrated on a quiet cpu-backend box; a cross-backend run or a
    # noise-bound baseline refuses instead of judging.
    halves_drift = abs(p50(base_times[:timed_cycles // 2])
                       - p50(base_times[timed_cycles // 2:])) / base_p50
    row["baseline_half_drift_pct"] = round(halves_drift * 100, 2)
    refusal = None
    if BACKEND.get("backend") not in ("cpu", "unknown"):
        refusal = (f"overhead bound calibrated on cpu; run on "
                   f"{BACKEND.get('backend')}")
    elif halves_drift > 0.03:
        refusal = (f"baseline cycle p50 drifted {halves_drift * 100:.1f}% "
                   f"between run halves — the box is too noisy to "
                   f"resolve a 1% overhead bound")
    if refusal is not None:
        row["rangespec_ok"] = None
        row["rangespec_refused"] = refusal
        record_refusal("bench.visibility_storm", "cycle_overhead",
                       refusal, "cpu")
        log(row)
        return row
    violations = []
    if overhead_p50 > 0.01:
        violations.append(
            f"admission-cycle p50 overhead {overhead_p50 * 100:.2f}% "
            f"with readers attached exceeds 1%")
    if overhead_p99 > 0.01:
        violations.append(
            f"admission-cycle p99 overhead {overhead_p99 * 100:.2f}% "
            f"with readers attached exceeds 1%")
    row["rangespec_ok"] = not violations
    if violations:
        row["rangespec_violation"] = "; ".join(violations)
        log(row)
        raise AssertionError(row["rangespec_violation"])
    log(row)
    return row


def bench_e2e_shallow(cycles=5):
    """The old light scenario: small workloads, first flavor always fits
    (the sequential assigner's best case — kept for honesty; the solver
    runs the production config: resident state + pipelined dispatch)."""
    from kueue_tpu.solver import BatchSolver

    out = {}
    for label, mk in (("solver", BatchSolver), ("cpu", lambda: None)):
        times, admitted, _ = _run_e2e(mk(),
                                      cycles + (5 if label == "solver"
                                                else 1),
                                      cpu_units=4, label=label,
                                      pipeline=(label == "solver"),
                                      routed=(label == "solver"))
        tp50 = p50(times)
        out[label] = tp50
        log({"bench": f"e2e_shallow_{label}", "p50_ms": round(tp50 * 1e3, 1),
             "admitted_per_sec": round(admitted / len(times) / tp50, 1)})
    return out["cpu"] / out["solver"]


def _admit_victim(cache, name, lq, cq, milli, priority, creation):
    from kueue_tpu.api import kueue as api
    from kueue_tpu.core import workload as wlpkg
    wl = make_workload(name, lq, cpu_units=0, priority=priority,
                       creation=creation)
    wl.spec.pod_sets[0].template.spec.containers[0].requests = {
        "cpu": milli, "memory": milli << 20}
    admission = api.Admission(
        cluster_queue=cq,
        pod_set_assignments=[api.PodSetAssignment(
            name="main", flavors={"cpu": "f0", "memory": "f0"},
            resource_usage={"cpu": milli, "memory": milli << 20},
            count=1)])
    wlpkg.set_quota_reservation(wl, admission, creation)
    cache.add_or_update_workload(wl)
    return wl


# Device-vs-CPU speedup floors for the preemption / fair-sharing bench
# regimes (ISSUE 9 acceptance; ROADMAP item 2's "no CPU-won regime"
# contract). Calibrated on a real device backend — a cpu_fallback run
# REFUSES the comparison (rangespec_refused) instead of minting a fake
# regression/regression-fix, per the PR-6 bench-env honesty policy.
PREEMPT_SPEEDUP_RANGESPEC_BACKEND = "tpu"
PREEMPT_SPEEDUP_FLOORS = {
    "preemption_heavy_cycle": 1.0,
    "fair_sharing_cycle": 1.0,
    "fair_preemption_cycle": 1.0,
}


def _speedup_rangespec_fields(name, speedup):
    """rangespec_ok / rangespec_refused fields for a regime row, via
    perf.checker.check_device_speedup (None = refused)."""
    floor = PREEMPT_SPEEDUP_FLOORS.get(name)
    if floor is None:
        return {}
    from kueue_tpu.perf.checker import (RangeSpec, check_device_speedup,
                                        record_refusal)
    spec = RangeSpec(backend=PREEMPT_SPEEDUP_RANGESPEC_BACKEND,
                     min_device_speedup=floor)
    ok, note = check_device_speedup(speedup, spec, BACKEND)
    out = {"rangespec_ok": ok}
    if ok is None:
        out["rangespec_refused"] = note
        # device-witness debt manifest: unjudged floors a device run
        # must witness (PR-9 carried thread)
        record_refusal(f"bench.{name}", "min_device_speedup", note,
                       spec.backend)
    elif not ok:
        out["rangespec_violation"] = note
    return out


def _log_gated_speedup_row(name, row, speedup):
    """Stamp a regime row with its device-speedup rangespec verdict,
    emit it, and fail the run on a witnessed violation; refusals pass
    through with the reason recorded (the cross-backend honesty
    policy). One enforcement point for every gated regime row."""
    row.update(_speedup_rangespec_fields(name, speedup))
    log(row)
    if row.get("rangespec_ok") is False:
        raise AssertionError(row.get("rangespec_violation"))


def _run_preempt_pair(build, name, extra, routed=False):
    """Run a preemption scenario on the CPU-only and solver-configured
    schedulers; assert identical evictions and report the wall times.
    routed=True runs the device side under the adaptive engine router,
    carrying its learned per-engine rates across the repeat builds (a
    long-running manager's steady state): scenarios the device can't pay
    for converge to CPU speed instead of paying solver-path overhead."""
    import gc
    gc.collect()  # earlier rows' garbage must not land in a timed window
    out = {}
    preempt_plan = []
    runs = 4 if routed else 2
    for label, solver in (("cpu", False), ("device", True)):
        # warmup run compiles the bucketed shapes; each timed run rebuilds
        # the identical scenario so the jit cache is hot. min-of-N damps
        # tunnel latency variance.
        sched, client = build(solver)
        sched.schedule(timeout=0)
        samples = sched.solver._sync_samples if sched.solver else None
        route_stats = None
        best = None
        for _ in range(runs):  # symmetric draws: min-of-N must compare like with like
            sched, client = build(solver)
            if sched.solver is not None and samples:
                sched.solver._sync_samples = list(samples)  # carry the floor
            if routed and solver:
                sched.solver_routing = "adaptive"
                if route_stats is not None:  # carry learned engine rates
                    # ... including the sticky regime predictor: a fresh
                    # scheduler predicting "fit" would re-enter mandatory
                    # sampling for a preempt-regime scenario every build
                    sched._route_stats, sched._last_regime = route_stats
            gc.collect()  # a prior run's garbage must not land in this window
            t0 = time.perf_counter()
            sched.schedule(timeout=0)
            dt = time.perf_counter() - t0
            if routed and solver:
                route_stats = (sched._route_stats, sched._last_regime)
            if best is None or dt < best[0]:
                best = (dt, client.evicted, sched.preemption_fallbacks)
            if solver and sched.last_preempt_plan:
                preempt_plan.append(sched.last_preempt_plan)
        out[label] = best
    (t_cpu, ev_cpu, _), (t_dev, ev_dev, fb) = out["cpu"], out["device"]
    assert ev_cpu == ev_dev and ev_dev > 0 and fb == 0, (ev_cpu, ev_dev, fb)
    speedup = t_cpu / t_dev
    row = {"bench": name, **extra, "evictions": ev_dev,
           "cpu_ms": round(t_cpu * 1e3, 1),
           "device_ms": round(t_dev * 1e3, 1),
           "speedup": round(speedup, 2)}
    if preempt_plan:
        # last device preempt-plan stats (pool / scanned / fill-back
        # rounds), same producer as /debug/router — witnesses that the
        # batched path actually ran, not the CPU fallback
        row["preempt_plan"] = preempt_plan[-1]
    _log_gated_speedup_row(name, row, speedup)
    return speedup


def bench_fair_sharing(num_cqs=2048, num_cohorts=256, cycles=4):
    """Fair sharing ON at the flagship shape: every admission borrows
    from its cohort, so the device computes the DRF dominant-share sort
    key for the whole batch (kernel._drf_share — the masked max-ratio
    reduction of clusterqueue.go:529-564) while the CPU path computes it
    per entry in nominate. The device path runs the production config
    (resident state + pipelined dispatch — fair fit-mode cycles qualify)."""
    import gc
    gc.collect()  # see _run_preempt_pair
    from kueue_tpu.solver import BatchSolver

    out = {}
    for label, solver in (("cpu", False), ("device", True)):
        sched, cache, queues, client, clock = build_env(
            num_cqs, num_cohorts, ["f0"], nominal_units=2,
            solver=BatchSolver() if solver else None, fair_sharing=True,
            pipeline=solver, routed=solver)
        n = 0
        warmup = 3 if solver else 1
        # identical wave population for both labels (warmup differs, so
        # size by the larger one): drained totals must be comparable
        for wave in range(cycles + 3 + 1):
            for i in range(num_cqs):
                # 4 units vs nominal 2: every admission borrows, so DRF
                # shares move each cycle
                wl = make_workload(f"w{wave}-{i}", f"lq{i}", cpu_units=4,
                                   priority=n % 5, creation=float(n))
                queues.add_or_update_workload(wl)
                n += 1

        def run_cycle():
            # Steady state: last cycle's admissions complete (freeing
            # their borrowed capacity through the cache — the solver sees
            # them as journal corrections), so every cycle admits a fresh
            # borrowing wave and recomputes DRF shares for the full batch.
            # The completion also flushes the cohort's parked inadmissible
            # entries, exactly as the workload controller does on delete
            # (manager.go:381) — without it, a wave parked NoFit during
            # the pipeline's one-cycle completion lag would strand.
            for wl in client.drain_applied():
                cache.delete_workload(wl)
                queues.queue_associated_inadmissible_workloads_after(wl)
            sched.schedule(timeout=0)

        for _ in range(warmup):  # compiles fair kernel + deltas variants
            run_cycle()
        before = client.admitted
        times = []
        for _ in range(cycles):
            t0 = time.perf_counter()
            run_cycle()
            times.append(time.perf_counter() - t0)
        while sched._inflight is not None:
            t0 = time.perf_counter()
            run_cycle()
            times.append(time.perf_counter() - t0)
        rate = (client.admitted - before) / len(times)
        # drain the remaining waves untimed so the total is comparable
        # exactly (the pipeline only shifts WHICH cycle admits a wave,
        # never whether it admits); stop once a full cycle makes no
        # progress with nothing in flight
        drained = 0
        while queues.pending_total() > 0 or sched._inflight is not None:
            prev = client.admitted
            run_cycle()
            if client.admitted == prev and sched._inflight is None:
                break
            drained += 1
            assert drained < 64, "fair_sharing drain did not converge"
        out[label] = (p50(times), rate, client.admitted)
    (t_cpu, adm_cpu, tot_cpu), (t_dev, adm_dev, tot_dev) = \
        out["cpu"], out["device"]
    # exact decision equality on the drained totals (the pipelined
    # window shift can't hide drift here)
    assert adm_dev > 0 and tot_cpu == tot_dev, (tot_cpu, tot_dev)
    speedup = t_cpu / t_dev
    row = {"bench": "fair_sharing_cycle", "cqs": num_cqs,
           "admitted_per_cycle": round(adm_dev, 1),
           "cpu_p50_ms": round(t_cpu * 1e3, 1),
           "device_p50_ms": round(t_dev * 1e3, 1),
           "speedup": round(speedup, 2)}
    _log_gated_speedup_row("fair_sharing_cycle", row, speedup)
    return speedup


def bench_fair_preemption(num_cqs=512, num_cohorts=64, victims_per_cq=12):
    """fairPreemptions at scale: every CQ over-borrows with small
    victims; a high-priority preemptor per CQ forces the DRF-heap loop
    (pop max-share CQ -> strategy test -> remove -> re-heap,
    preemption.go:312-437) — sequential per entry on CPU, one vmapped
    scan lane per entry on device (solver/fairpreempt.py)."""
    from kueue_tpu.api import kueue as api
    from kueue_tpu.solver import BatchSolver

    preemption = api.ClusterQueuePreemption(
        within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
        reclaim_within_cohort=api.PREEMPTION_ANY)

    def build(solver):
        # cq{i} is in cohort i % num_cohorts, so cohort c's members are
        # {c, c+num_cohorts, ...}. Member 0 of each cohort (i <
        # num_cohorts) stays idle and hosts the preemptor; every other
        # member over-borrows with small victims, so the preemptor's CQ
        # has the LOWEST share and the DRF-heap loop must drain the
        # borrowers share-by-share until the preemptor fits.
        sched, cache, queues, client, clock = build_env(
            num_cqs, num_cohorts, ["f0"], nominal_units=8,
            solver=BatchSolver() if solver else None,
            preemption=preemption, fair_sharing=True)
        # Borrowers run slightly over their nominal 8 while the cohort
        # stays within total capacity (the preemptor must be satisfiable):
        # borrowers * total <= capacity - headroom, so each preemptor
        # forces a long run of share-ordered removals.
        members = num_cqs // num_cohorts
        borrowers = members - 1
        # leave LESS free capacity than the preemptor's 8-unit ask (so
        # preemption is required) while borrowers stay above nominal 8
        # and the cohort stays within total capacity (so it can succeed)
        per_borrower = (members * 8000 - 2000) // borrowers
        victim_milli = per_borrower // victims_per_cq
        for i in range(num_cqs):
            if i >= num_cohorts:
                for v in range(victims_per_cq):
                    _admit_victim(cache, f"victim{i}-{v}", f"lq{i}",
                                  f"cq{i}", victim_milli, 0, float(v))
            else:
                queues.add_or_update_workload(
                    make_workload(f"preemptor{i}", f"lq{i}", cpu_units=8,
                                  priority=10, creation=1000.0))
        return sched, client

    return _run_preempt_pair(build, "fair_preemption_cycle",
                             {"cqs": num_cqs, "fair_sharing": True},
                             routed=True)


def bench_preemption_small(num_cqs=256, num_cohorts=32, victims_per_cq=4):
    """Small within-CQ preemption: 4 candidates per problem. The CPU
    simulation is trivial here, so the solver's work gate must route
    target selection to the CPU preemptor — reported speedup should be
    ~1.0 (the gate's job), not a device win."""
    from kueue_tpu.api import kueue as api
    from kueue_tpu.solver import BatchSolver

    preemption = api.ClusterQueuePreemption(
        within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
        reclaim_within_cohort=api.PREEMPTION_ANY)

    def build(solver):
        sched, cache, queues, client, clock = build_env(
            num_cqs, num_cohorts, ["f0"], nominal_units=8,
            solver=BatchSolver() if solver else None, preemption=preemption)
        for i in range(num_cqs):
            for v in range(victims_per_cq):
                _admit_victim(cache, f"victim{i}-{v}", f"lq{i}", f"cq{i}",
                              2000, 0, float(v))
            queues.add_or_update_workload(
                make_workload(f"preemptor{i}", f"lq{i}", cpu_units=4,
                              priority=10, creation=1000.0))
        return sched, client

    return _run_preempt_pair(build, "preemption_small_cycle",
                             {"cqs": num_cqs}, routed=True)


def bench_preemption_reclaim(num_roots=128, children_per_root=2,
                             cqs_per_child=8, victims_per_borrower=36):
    """Reclaim-heavy preemption at the flagship shape with HIERARCHICAL
    cohorts (the v1alpha1 Cohort tree): 2048 CQs in 256 child cohorts
    under 128 roots. Every non-lender CQ overflows its nominal quota with
    small victims (borrowing), and a large high-priority preemptor per CQ
    must reclaim deep — within-CQ problems remove ~16 of 18 victims,
    under-nominal reclaim problems see ~250 candidates across the root's
    subtree, and every removal/fill-back walks the depth-2 cohort chain.
    This is the regime where minimalPreemptions' sequential simulate /
    fill-back (preemption.go:237-310 + resource_node.go chain math)
    dominates the CPU cycle and the batched device scan pays."""
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import ObjectMeta
    from kueue_tpu.solver import BatchSolver

    num_children = num_roots * children_per_root
    num_cqs = num_children * cqs_per_child
    preemption = api.ClusterQueuePreemption(
        within_cluster_queue=api.PREEMPTION_LOWER_PRIORITY,
        reclaim_within_cohort=api.PREEMPTION_ANY)

    def build(solver):
        # cq{i} is in child cohort-(i % num_children); child c's parent is
        # root-(c // children_per_root); cq0..cq{num_children-1} (one per
        # child) are the idle lenders.
        sched, cache, queues, client, clock = build_env(
            num_cqs, num_children, ["f0"], nominal_units=8,
            solver=BatchSolver() if solver else None, preemption=preemption)
        for c in range(num_children):
            cohort = api.Cohort(metadata=ObjectMeta(name=f"cohort-{c}",
                                                    uid=f"co-{c}"))
            cohort.spec.parent = f"root-{c // children_per_root}"
            cache.add_or_update_cohort(cohort)
        victim_milli = 9000 // victims_per_borrower
        for i in range(num_cqs):
            if i >= num_children:
                for v in range(victims_per_borrower):
                    _admit_victim(cache, f"victim{i}-{v}", f"lq{i}",
                                  f"cq{i}", victim_milli, 0, float(v))
            queues.add_or_update_workload(
                make_workload(f"preemptor{i}", f"lq{i}", cpu_units=8,
                              priority=10, creation=1000.0))
        return sched, client

    reclaim_k = (cqs_per_child * children_per_root - children_per_root) \
        * victims_per_borrower
    # routed like every other row: the production config — on a backend
    # where the batched scan loses (XLA-CPU fallback), the router
    # converges to the CPU preemptor; on the TPU it keeps the device.
    return _run_preempt_pair(build, "preemption_heavy_cycle",
                             {"cqs": num_cqs, "cohort_depth": 2,
                              "candidates_per_reclaim": reclaim_k},
                             routed=True)


def bench_depth4_cohorts(num_cqs=2048, num_leaves=256, num_mids=128,
                         num_roots=64, cycles=4):
    """Depth-4 cohort chains (CQ -> leaf -> mid -> root) at the flagship
    CQ scale: every availability walk and usage bubble traverses 3 cohort
    levels, and the kernel unrolls its chain loops to the tree's max
    depth (kernel.py:50-67) — this row prices that unrolling (VERDICT r3
    ask #7). Lending limits are unset, so guaranteed quota is zero and
    every admission bubbles its full usage through the 3-level chain;
    completions recycle capacity each cycle and quota is sized so the
    pipeline's one in-flight wave never starves admissions."""
    from kueue_tpu.api import kueue as api
    from kueue_tpu.api.meta import ObjectMeta
    import gc
    gc.collect()  # see _run_preempt_pair
    from kueue_tpu.solver import BatchSolver

    out = {}
    for label, solver in (("cpu", False), ("device", True)):
        sched, cache, queues, client, clock = build_env(
            num_cqs, num_leaves, ["f0"], nominal_units=16,
            solver=BatchSolver() if solver else None, pipeline=solver)
        for leaf in range(num_leaves):
            c = api.Cohort(metadata=ObjectMeta(name=f"cohort-{leaf}",
                                               uid=f"co-{leaf}"))
            c.spec.parent = f"mid-{leaf % num_mids}"
            cache.add_or_update_cohort(c)
        for m in range(num_mids):
            c = api.Cohort(metadata=ObjectMeta(name=f"mid-{m}",
                                               uid=f"mid-{m}"))
            c.spec.parent = f"root-{m % num_roots}"
            cache.add_or_update_cohort(c)
        n = 0
        warmup = 3 if solver else 1
        for wave in range(cycles + warmup + 1):
            for i in range(num_cqs):
                wl = make_workload(f"w{wave}-{i}", f"lq{i}", cpu_units=4,
                                   priority=n % 5, creation=float(n))
                queues.add_or_update_workload(wl)
                n += 1

        def run_cycle():
            for wl in client.drain_applied():
                cache.delete_workload(wl)
            sched.schedule(timeout=0)

        for _ in range(warmup):
            run_cycle()
        before = client.admitted
        times = []
        for _ in range(cycles):
            t0 = time.perf_counter()
            run_cycle()
            times.append(time.perf_counter() - t0)
        while sched._inflight is not None:
            t0 = time.perf_counter()
            run_cycle()
            times.append(time.perf_counter() - t0)
        out[label] = (p50(times), (client.admitted - before) / len(times))
    (t_cpu, adm_cpu), (t_dev, adm_dev) = out["cpu"], out["device"]
    assert adm_dev > 0 and abs(adm_cpu - adm_dev) <= 0.2 * max(adm_cpu, 1), \
        (adm_cpu, adm_dev)
    log({"bench": "depth4_cohort_cycle", "cqs": num_cqs, "cohort_depth": 4,
         "admitted_per_cycle": round(adm_dev, 1),
         "cpu_p50_ms": round(t_cpu * 1e3, 1),
         "device_p50_ms": round(t_dev * 1e3, 1),
         "speedup": round(t_cpu / t_dev, 2)})
    return t_cpu / t_dev


def bench_cold_start(num_cqs=32, num_cohorts=8, budget_s=240.0):
    """Compile-storm immunity (solver/warmgov.py + solver/COMPILE.md,
    ROADMAP item 4): process start -> first device-routed cycle, with
    and without a primed persistent compilation cache.

    Each "process start" is a fresh KueueManager + BatchSolver with the
    in-process jit cache cleared (jax.clear_caches()) and the
    warmed-program registry reset — the in-process equivalent of a
    restart. The compile governor launches at manager construction
    (solver.warmupAtStartup); until the traffic's shape bucket is warm,
    cycles route "cpu-warmup" (admissions keep flowing on the CPU
    path), and the first device-routed cycle marks cold-start done.

    Asserts: both starts reach a device-routed cycle within the budget;
    ZERO mid-traffic compiles (every device-dispatched program variant
    was warmed first — the cpu-warmup gate held until then); and, when
    the backend's persistent cache works (entries on disk after the
    cold start), the primed start performs zero fresh compiles (pure
    cache load, checked via jax's compilation-cache events) and beats
    the cold one."""
    import shutil
    import tempfile

    import jax

    from kueue_tpu import config as cfgpkg
    from kueue_tpu.api.meta import FakeClock
    from kueue_tpu.manager import KueueManager
    from kueue_tpu.solver import BatchSolver
    from kueue_tpu.solver import service as svc
    from kueue_tpu.solver import warmgov
    from kueue_tpu.utils.runtime import enable_compilation_cache

    cache_dir = tempfile.mkdtemp(prefix="kueue-coldstart-")

    def one_start(label):
        jax.clear_caches()
        svc.reset_seen_programs()
        cfg = cfgpkg.Configuration()
        cfg.solver.enable = True
        cfg.solver.min_heads = 0
        cfg.solver.compile_cache_dir = cache_dir
        cfg.solver.warmup_at_startup = True
        clock = FakeClock(1000.0)
        t0 = time.perf_counter()
        mgr = KueueManager(cfg=cfg, clock=clock, solver=BatchSolver())
        # Production deployments size the arena up front (the perf
        # harness passes expected_pending) so the arena-gather variants
        # warm at the real capacity instead of compiling on the first
        # arena dispatch.
        mgr.warm_governor.expected_pending = num_cqs * 4
        for obj in ([make_flavor("f0")]
                    + [make_cq(f"cq{i}", f"cohort-{i % num_cohorts}",
                               ["f0"], nominal_units=100_000)
                       for i in range(num_cqs)]
                    + [make_lq(f"lq{i}", f"cq{i}")
                       for i in range(num_cqs)]):
            mgr.store.create(obj)
        mgr.run_until_idle(max_iterations=1_000_000)
        n = 0
        first_device_s = None
        waves = 0
        while time.perf_counter() - t0 < budget_s:
            for i in range(num_cqs):
                wl = make_workload(f"{label}-w{n}", f"lq{i}", cpu_units=1,
                                   creation=float(n))
                mgr.store.create(wl)
                n += 1
            mgr.run_until_idle(max_iterations=1_000_000)
            mgr.scheduler.schedule(timeout=0)
            mgr.run_until_idle(max_iterations=1_000_000)
            clock.advance(1.0)
            waves += 1
            counts = mgr.scheduler.cycle_counts
            if (counts.get("device", 0) + counts.get("device-pipelined", 0)
                    + counts.get("device-dispatch-only", 0)) >= 1:
                first_device_s = time.perf_counter() - t0
                break
            time.sleep(0.25)  # let the background ladder make progress
        # Drain the ladder before "process shutdown": the measurement
        # stops at the first device cycle, but the smaller drain
        # buckets may still be warming in the background — stopping
        # mid-compile would leave them un-persisted, and the primed
        # run would (correctly!) compile them fresh.
        t_drain = time.perf_counter()
        while (mgr.warm_governor.state == warmgov.GOV_WARMING
               and time.perf_counter() - t_drain < budget_s):
            time.sleep(0.1)
        st = mgr.warm_governor.status()
        mgr.warm_governor.stop()
        mid = mgr.scheduler.solver.counters["mid_traffic_compiles"]
        # Fresh compiles attributed per bucket (the provenance deltas),
        # not raw process-wide cache misses — warm_setup's zero-fill
        # compiles outside the buckets are not ladder programs.
        fresh = sum(1 for b in st["buckets"] if b["source"] == "fresh")
        return {"first_device_cycle_s": first_device_s, "waves": waves,
                "cpu_warmup_cycles":
                    mgr.scheduler.cycle_counts.get("cpu-warmup", 0),
                "mid_traffic_compiles": mid, "fresh_buckets": fresh,
                "warmup_state": st["state"],
                "warmup_faults": st["warmup_faults"]}

    try:
        cold = one_start("cold")
        # Did the backend's persistent cache actually persist anything?
        # (Provenance classification degrades gracefully without it.)
        cache_supported = any(files for _, _, files in os.walk(cache_dir))
        primed = one_start("primed")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
        enable_compilation_cache()  # restore the shared bench cache dir

    assert cold["first_device_cycle_s"] is not None, \
        f"cold start never reached a device cycle within {budget_s}s"
    assert primed["first_device_cycle_s"] is not None, \
        f"primed start never reached a device cycle within {budget_s}s"
    # Zero mid-traffic compiles: the cpu-warmup gate held every cycle
    # off the device route until its bucket was warm.
    assert cold["mid_traffic_compiles"] == 0, cold
    assert primed["mid_traffic_compiles"] == 0, primed
    if cache_supported:
        # Cache reuse is asserted structurally (zero fresh buckets);
        # the latency ratio is reported but not asserted — a single
        # wall-clock sample comparison is noise-bound when compiles are
        # cheap relative to the drive loop's quantization.
        assert primed["fresh_buckets"] == 0, primed

    log({"bench": "cold_start", "cqs": num_cqs,
         "budget_s": budget_s, "cache_supported": cache_supported,
         "cold": cold, "primed": primed,
         "primed_speedup": round(
             cold["first_device_cycle_s"]
             / max(primed["first_device_cycle_s"], 1e-9), 2)})
    return cold["first_device_cycle_s"], primed["first_device_cycle_s"]


# Crash-restart recovery budgets (ISSUE 10 acceptance). The cycle
# bound is VIRTUAL/structural — cycles from restore() to the first
# admitted cycle — so it is backend-agnostic and always asserted. The
# wall bound covers restore() itself (checkpoint load + WAL replay +
# reconcile settle) and was calibrated on XLA-CPU host runs, so per
# the perf.checker honesty policy it declares backend "cpu" and the
# comparison is REFUSED (rangespec_refused) on any other backend
# instead of minting a fake verdict.
RESTART_RECOVERY_RANGESPEC_BACKEND = "cpu"
RESTART_RECOVERY_MAX_RESTORE_WALL_S = 10.0
RESTART_RECOVERY_MAX_CYCLES_TO_ADMIT = 3


def bench_restart_recovery(num_cqs=16, num_cohorts=4, waves=4,
                           budget_s=240.0):
    """Crash-restart durability (resilience/recovery.py +
    RESILIENCE.md §6): two full process lifetimes sharing one
    persistent compilation cache dir. Each life runs the production
    config (durable store + solver + compile governor), is killed by an
    injected crash at a store-write mid-traffic, and is restored from
    the durable store into a "new process" (jit caches cleared, warmed
    registry reset, fresh BatchSolver).

    Measured per recovery: restore() wall seconds (load + replay +
    settle), cycles from restore to the first admitted cycle, and
    compile provenance during recovery. Asserts: both recoveries admit
    within RESTART_RECOVERY_MAX_CYCLES_TO_ADMIT cycles (the cpu-warmup
    gate keeps admission flowing while buckets warm — recovery never
    waits on a compile); zero mid-traffic compiles; and the SECOND
    life's recovery — running against the cache the first life
    persisted — performs zero fresh bucket compiles (pure cache load),
    the "etcd is the checkpoint, restart is cheap" property end-to-end
    (SURVEY.md §5)."""
    import shutil
    import tempfile

    import jax

    from kueue_tpu import config as cfgpkg
    from kueue_tpu.api.meta import FakeClock
    from kueue_tpu.manager import KueueManager
    from kueue_tpu.resilience import faultinject, recovery
    from kueue_tpu.resilience.faultinject import (
        CRASH, FaultInjector, InjectedCrash)
    from kueue_tpu.solver import BatchSolver
    from kueue_tpu.solver import service as svc
    from kueue_tpu.solver import warmgov
    from kueue_tpu.utils.runtime import enable_compilation_cache

    cache_dir = tempfile.mkdtemp(prefix="kueue-restart-")

    def make_cfg():
        cfg = cfgpkg.Configuration()
        cfg.solver.enable = True
        cfg.solver.min_heads = 0
        cfg.solver.routing = "always"
        cfg.solver.compile_cache_dir = cache_dir
        cfg.solver.warmup_at_startup = True
        cfg.store.durable = True
        return cfg

    def drive_cycle(mgr, clock, label, wave, n):
        for i in range(num_cqs):
            mgr.store.create(make_workload(f"{label}-w{n}", f"lq{i}",
                                           cpu_units=1,
                                           creation=float(n)))
            n += 1
        mgr.run_until_idle(max_iterations=1_000_000)
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        return n

    def one_life(label):
        """Fresh process -> traffic -> seeded kill. Returns the
        durable log (the state that survives) and the shared clock."""
        jax.clear_caches()
        svc.reset_seen_programs()
        clock = FakeClock(1000.0)
        mgr = KueueManager(cfg=make_cfg(), clock=clock,
                           solver=BatchSolver())
        for obj in ([make_flavor("f0")]
                    + [make_cq(f"cq{i}", f"cohort-{i % num_cohorts}",
                               ["f0"], nominal_units=100_000)
                       for i in range(num_cqs)]
                    + [make_lq(f"lq{i}", f"cq{i}")
                       for i in range(num_cqs)]):
            mgr.store.create(obj)
        mgr.run_until_idle(max_iterations=1_000_000)
        n = 0
        for wave in range(waves):
            n = drive_cycle(mgr, clock, label, wave, n)
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {5: CRASH}}))
        crashed = False
        try:
            drive_cycle(mgr, clock, label, waves, n)
        except InjectedCrash:
            crashed = True
        finally:
            faultinject.uninstall()
        assert crashed, "kill point never fired"
        # In-process simulation hygiene (a real SIGKILL needs none):
        # the dead life's background governor thread must not keep
        # compiling into the module-global program registry while the
        # "new process" resets it — that would mask real mid-traffic
        # compiles and skew the primed-run provenance.
        mgr.warm_governor.stop()
        return mgr.durable, clock

    def one_recovery(durable, clock, label):
        """The 'new process': cleared jit caches, fresh solver —
        everything it reuses must come from the durable store or the
        persistent compilation cache."""
        jax.clear_caches()
        svc.reset_seen_programs()
        t0 = time.perf_counter()
        mgr = recovery.restore(durable, cfg=make_cfg(), clock=clock,
                               solver=BatchSolver())
        restore_wall_s = mgr.last_recovery.duration_s
        n = 100_000  # fresh names: pre-crash arrivals are durable
        cycles_to_admit = None
        before = mgr.recorder.reason_counts.get("QuotaReserved", 0)
        for cycle in range(10):
            if time.perf_counter() - t0 > budget_s:
                break
            n = drive_cycle(mgr, clock, label, cycle, n)
            if mgr.recorder.reason_counts.get("QuotaReserved",
                                              0) > before:
                cycles_to_admit = cycle + 1
                break
        # Drain the warm ladder before "shutdown" so this life's
        # compiles persist for the next one (cold_start's contract).
        t_drain = time.perf_counter()
        while (mgr.warm_governor.state == warmgov.GOV_WARMING
               and time.perf_counter() - t_drain < budget_s):
            time.sleep(0.1)
        st = mgr.warm_governor.status()
        fresh = sum(1 for b in st["buckets"] if b["source"] == "fresh")
        mid = mgr.scheduler.solver.counters["mid_traffic_compiles"]
        rep = mgr.last_recovery.to_dict()
        mgr.shutdown()
        return {"restore_wall_s": round(restore_wall_s, 4),
                "cycles_to_first_admission": cycles_to_admit,
                "mid_traffic_compiles": mid, "fresh_buckets": fresh,
                "warmup_state": st["state"],
                "admitted_restored": rep["admitted_restored"],
                "wal_records_replayed": rep["wal_records_replayed"]}

    try:
        d1, clk1 = one_life("life1")
        cold = one_recovery(d1, clk1, "rec1")
        cache_supported = any(files for _, _, files in os.walk(cache_dir))
        d2, clk2 = one_life("life2")
        primed = one_recovery(d2, clk2, "rec2")
    finally:
        faultinject.uninstall()
        shutil.rmtree(cache_dir, ignore_errors=True)
        enable_compilation_cache()  # restore the shared bench cache dir

    # Backend-agnostic gates: recovery admits within the cycle bound
    # and never pays a hot-path compile (the cpu-warmup gate holds).
    for name, rec in (("cold", cold), ("primed", primed)):
        assert rec["cycles_to_first_admission"] is not None \
            and rec["cycles_to_first_admission"] \
            <= RESTART_RECOVERY_MAX_CYCLES_TO_ADMIT, (name, rec)
        assert rec["mid_traffic_compiles"] == 0, (name, rec)
    # The primed recovery rode the persistent cache: zero fresh bucket
    # compiles (structural proof, like cold_start's). Only assertable
    # when the first life's recovery finished its ladder within budget
    # (so every bucket persisted) — a drain cut short leaves buckets
    # the second life must legitimately compile fresh.
    primed_verifiable = (cache_supported
                         and cold["warmup_state"] != warmgov.GOV_WARMING)
    if primed_verifiable:
        assert primed["fresh_buckets"] == 0, primed

    # Wall budget: calibrated on "cpu" — refuse cross-backend instead
    # of judging (perf.checker honesty policy, ISSUE 10 satellite).
    from kueue_tpu.perf.checker import RangeSpec, refuse_cross_backend
    spec = RangeSpec(backend=RESTART_RECOVERY_RANGESPEC_BACKEND,
                     max_wall_s=RESTART_RECOVERY_MAX_RESTORE_WALL_S)
    refusal = refuse_cross_backend(spec, BACKEND)
    row = {"bench": "restart_recovery", "cqs": num_cqs, "waves": waves,
           "cache_supported": cache_supported,
           "primed_fresh_verified": primed_verifiable,
           "cold": cold, "primed": primed,
           "max_cycles_to_admit": RESTART_RECOVERY_MAX_CYCLES_TO_ADMIT,
           "rangespec": {"backend": spec.backend,
                         "max_restore_wall_s": spec.max_wall_s}}
    if refusal is not None:
        row["rangespec_ok"] = None
        row["rangespec_refused"] = refusal
    else:
        worst = max(cold["restore_wall_s"], primed["restore_wall_s"])
        row["rangespec_ok"] = worst <= spec.max_wall_s
        if not row["rangespec_ok"]:
            row["rangespec_violation"] = (
                f"restore wall {worst:.3f}s exceeds "
                f"{spec.max_wall_s:.1f}s")
            log(row)
            raise AssertionError(row["rangespec_violation"])
    log(row)
    return cold["restore_wall_s"], primed["restore_wall_s"]


FAILOVER_MAX_CYCLES_TO_ADMIT = 3


def bench_failover_recovery(num_cqs=16, num_cohorts=4, waves=4,
                            budget_s=240.0):
    """Hot-standby failover A/B (resilience/replica.py +
    RESILIENCE.md §7): one leader life over a durable log with a
    StandbyReplica tailing the WAL every cycle, both running the
    production config (solver + compile governor) against ONE shared
    persistent compilation cache dir. The leader is killed by an
    injected crash at a store write; the log is cloned at that instant
    and recovery runs BOTH ways:

    - **warm**: the follower promotes (fence + tail drain — its
      manager, caches and solver warm investment already live);
    - **cold**: a PR-10 restore from the clone into a "new process"
      (jit caches cleared, warmed registry reset, fresh BatchSolver),
      timed through the follower's incremental replay path AND the
      legacy collapsed replay on a second clone — the ISSUE 15
      carried-thread delta.

    Asserts (backend-agnostic): replication lag drains to zero at
    every poll during the storm (bounded throughout); both arms admit
    within FAILOVER_MAX_CYCLES_TO_ADMIT cycles; the warm promotion's
    recovery wall is strictly under the cold restore's (same host,
    back-to-back — the structural claim the subsystem exists for);
    zero mid-traffic compiles after promotion; and nothing durably
    admitted before the kill is lost by either arm."""
    import shutil
    import tempfile

    import jax

    from kueue_tpu import config as cfgpkg
    from kueue_tpu.api.meta import FakeClock
    from kueue_tpu.core import workload as wlpkg
    from kueue_tpu.manager import KueueManager
    from kueue_tpu.resilience import faultinject, recovery
    from kueue_tpu.resilience.faultinject import (
        CRASH, FaultInjector, InjectedCrash)
    from kueue_tpu.resilience.replica import StandbyReplica, lead
    from kueue_tpu.solver import BatchSolver
    from kueue_tpu.solver import service as svc
    from kueue_tpu.utils.runtime import enable_compilation_cache

    cache_dir = tempfile.mkdtemp(prefix="kueue-failover-")

    def make_cfg():
        cfg = cfgpkg.Configuration()
        cfg.solver.enable = True
        cfg.solver.min_heads = 0
        cfg.solver.routing = "always"
        cfg.solver.compile_cache_dir = cache_dir
        cfg.solver.warmup_at_startup = True
        cfg.store.durable = True
        cfg.store.checkpoint_every = 256
        return cfg

    def drive_cycle(mgr, clock, label, n):
        for i in range(num_cqs):
            mgr.store.create(make_workload(f"{label}-w{n}", f"lq{i}",
                                           cpu_units=1,
                                           creation=float(n)))
            n += 1
        mgr.run_until_idle(max_iterations=1_000_000)
        mgr.scheduler.schedule(timeout=0)
        mgr.run_until_idle(max_iterations=1_000_000)
        clock.advance(1.0)
        return n

    def admitted_keys(mgr):
        return sorted(wlpkg.key(wl) for wl in mgr.store.list("Workload")
                      if wlpkg.has_quota_reservation(wl))

    def cycles_to_admit(mgr, clock, label, t0):
        before = mgr.recorder.reason_counts.get("QuotaReserved", 0)
        n = 100_000
        for cycle in range(10):
            if time.perf_counter() - t0 > budget_s:
                break
            n = drive_cycle(mgr, clock, label, n)
            if mgr.recorder.reason_counts.get("QuotaReserved",
                                              0) > before:
                return cycle + 1
        return None

    jax.clear_caches()
    svc.reset_seen_programs()
    clock = FakeClock(1000.0)
    leader = KueueManager(cfg=make_cfg(), clock=clock,
                          solver=BatchSolver())
    for obj in ([make_flavor("f0")]
                + [make_cq(f"cq{i}", f"cohort-{i % num_cohorts}",
                           ["f0"], nominal_units=100_000)
                   for i in range(num_cqs)]
                + [make_lq(f"lq{i}", f"cq{i}")
                   for i in range(num_cqs)]):
        leader.store.create(obj)
    leader.run_until_idle(max_iterations=1_000_000)
    durable = leader.durable
    lead(leader, durable, identity="leader-0")
    standby = StandbyReplica(durable, cfg=make_cfg(), clock=clock,
                             solver=BatchSolver(), identity="standby-0")

    try:
        # -- the storm: follower polls every cycle, lag must drain ----
        n = 0
        undrained_polls = 0
        for _wave in range(waves):
            n = drive_cycle(leader, clock, "life", n)
            standby.poll()
            if standby.lag_records != 0:
                undrained_polls += 1
        max_lag = standby.max_lag_records

        # -- the kill -------------------------------------------------
        faultinject.install(FaultInjector(
            {faultinject.SITE_STORE: {5: CRASH}}))
        crashed = False
        try:
            drive_cycle(leader, clock, "life", n)
        except InjectedCrash:
            crashed = True
        finally:
            faultinject.uninstall()
        assert crashed, "kill point never fired"
        leader.warm_governor.stop()  # in-process hygiene (bench_restart)
        pre_admitted = set(
            wlpkg.key(wl)
            for wl in durable.load().objects.get("Workload", {}).values()
            if wlpkg.has_quota_reservation(wl))
        # The cold arm must see EXACTLY the durable state the warm arm
        # promotes from — promotion checkpoints and journals onward, so
        # clone the log at the kill instant (twice: one per replay mode).
        clone_inc = durable.clone()
        clone_col = durable.clone()

        # -- warm arm: promote the follower ---------------------------
        t0 = time.perf_counter()
        promoted = standby.promote(force=True)
        warm_wall_s = standby.last_promotion.duration_s
        warm_cycles = cycles_to_admit(promoted, clock, "warm", t0)
        warm_mid = promoted.scheduler.solver.counters[
            "mid_traffic_compiles"]
        warm = {"recovery_wall_s": round(warm_wall_s, 4),
                "cycles_to_first_admission": warm_cycles,
                "mid_traffic_compiles": warm_mid,
                "drained_records":
                    standby.last_promotion.drained_records,
                "epoch": standby.last_promotion.epoch}
        warm_lost = sorted(pre_admitted - set(admitted_keys(promoted)))
        promoted.shutdown(checkpoint=False)

        # -- cold arm: restore from the clone into a "new process" ----
        jax.clear_caches()
        svc.reset_seen_programs()
        clock2 = FakeClock(clock.now())
        t0 = time.perf_counter()
        cold_mgr = recovery.restore(clone_inc, cfg=make_cfg(),
                                    clock=clock2, solver=BatchSolver())
        cold_wall_s = cold_mgr.last_recovery.duration_s
        cold_cycles = cycles_to_admit(cold_mgr, clock2, "cold", t0)
        cold_mid = cold_mgr.scheduler.solver.counters[
            "mid_traffic_compiles"]
        cold = {"recovery_wall_s": round(cold_wall_s, 4),
                "cycles_to_first_admission": cold_cycles,
                "mid_traffic_compiles": cold_mid,
                "replay_mode": cold_mgr.last_recovery.replay_mode,
                "wal_records_replayed":
                    cold_mgr.last_recovery.wal_records_replayed}
        cold_lost = sorted(pre_admitted - set(admitted_keys(cold_mgr)))
        cold_mgr.warm_governor.stop()
        cold_mgr.shutdown(checkpoint=False)

        # -- the carried-thread delta: incremental vs collapsed replay
        # (checkpoint_after left at its default on BOTH arms so the
        # delta compares replay modes, not checkpoint policy)
        clock3 = FakeClock(clock.now())
        col_mgr = recovery.restore(clone_col, cfg=make_cfg(),
                                   clock=clock3, solver=BatchSolver(),
                                   incremental=False)
        collapsed_wall_s = col_mgr.last_recovery.duration_s
        col_mgr.warm_governor.stop()
        col_mgr.shutdown(checkpoint=False)
    finally:
        faultinject.uninstall()
        shutil.rmtree(cache_dir, ignore_errors=True)
        enable_compilation_cache()  # restore the shared bench cache dir

    # Backend-agnostic gates.
    assert undrained_polls == 0, (
        f"{undrained_polls} poll(s) left replication lag undrained "
        f"during the storm")
    for name, rec in (("warm", warm), ("cold", cold)):
        assert rec["cycles_to_first_admission"] is not None \
            and rec["cycles_to_first_admission"] \
            <= FAILOVER_MAX_CYCLES_TO_ADMIT, (name, rec)
    assert warm["mid_traffic_compiles"] == 0, warm
    assert not warm_lost and not cold_lost, (warm_lost, cold_lost)
    # The structural A/B: the warm promotion beats the cold restore on
    # the same host, back-to-back — the follower's whole point.
    assert warm["recovery_wall_s"] < cold["recovery_wall_s"], \
        (warm, cold)

    row = {"bench": "failover_recovery", "cqs": num_cqs, "waves": waves,
           "warm_promotion": warm, "cold_restore": cold,
           "speedup": round(cold["recovery_wall_s"]
                            / max(warm["recovery_wall_s"], 1e-9), 1),
           "max_lag_records_during_storm": max_lag,
           "undrained_polls": undrained_polls,
           "incremental_restore_wall_s": round(cold_wall_s, 4),
           "collapsed_restore_wall_s": round(collapsed_wall_s, 4),
           "incremental_vs_collapsed_delta_s":
               round(collapsed_wall_s - cold_wall_s, 4),
           "max_cycles_to_admit": FAILOVER_MAX_CYCLES_TO_ADMIT}
    log(row)
    return warm["recovery_wall_s"], cold["recovery_wall_s"]


def bench_multihost():
    """ISSUE 13 MULTICHIP multi-host row: the weak-scaling curve
    (conflict domains per device held constant across 1/2/4/8 simulated
    hosts, via a subprocess forcing the host-platform device count
    before jax initializes) plus the cluster-column scoring cost at the
    north-star single-chip shape with simulated remote clusters.

    Target scenario: 1M pending workloads x 16k CQs x 32 flavors across
    simulated remote clusters. On anything but a real multi-host device
    deployment the sub-linear weak-scaling gate REFUSES judgement into
    the device-witness-debt manifest (simulated hosts share one
    machine's cores — total work grows with hosts while the hardware
    does not, so sub-linearity is physically unwitnessable here); the
    measured curve, layout balance and identity verdict are still
    recorded."""
    import subprocess

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kueue_tpu.perf.checker import record_refusal
    from kueue_tpu.solver.kernel import max_rank_bound, solve_cycle_fused
    from kueue_tpu.solver.synth import synth_solver_inputs

    row = {
        "bench": "multihost_scaling",
        "target_scenario": {"pending": 1_000_000, "cqs": 16_384,
                            "flavors": 32, "remote_clusters": 4,
                            "hosts": [1, 2, 4, 8]},
    }
    probe_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", "mesh_probe.py")
    verdict = None
    try:
        out = subprocess.run(
            [sys.executable, probe_path, "--hosts", "1,2,4,8",
             "--devices", "8", "--cqs-per-host", "256",
             "--wl-per-host", "512", "--cycles", "4",
             "--check-identity", "--json"],
            capture_output=True, text=True, timeout=560,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        verdict = json.loads(out.stdout.strip().splitlines()[-1])
        row["weak_scaling"] = verdict.get("weak_scaling")
        row["max_imbalance"] = verdict.get("max_imbalance")
        row["identity_failures"] = verdict.get("identity_failures")
        row["curve"] = [
            {k: r.get(k) for k in ("hosts", "devices", "occupied_domains",
                                   "planner_imbalance", "cycle_s_p50")}
            for r in verdict.get("rows", [])]
        row["probe_ok"] = bool(verdict.get("ok"))
    except Exception as exc:  # noqa: BLE001 — probe ENV trouble: record
        row["probe_error"] = f"{type(exc).__name__}: {exc}"[:300]
    if verdict is not None:
        # The acceptance gates live OUTSIDE the env-trouble containment:
        # a probe that RAN and found divergence or imbalance must fail
        # the bench, not file a probe_error.
        assert not verdict.get("identity_failures"), \
            "multi-host decisions diverge from the single-chip oracle"
        assert verdict.get("max_imbalance", 99.0) <= 1.5, \
            f"planner imbalance {verdict.get('max_imbalance')} > 1.5x"

    # Sub-linear weak scaling is a MULTI-HOST DEVICE property; judge it
    # only there (SUFFIX: simulated hosts on one machine refuse).
    ws = row.get("weak_scaling")
    if BACKEND.get("cpu_fallback", True) or BACKEND.get("backend") != "tpu":
        note = ("weak-scaling sub-linearity requires real multi-host "
                "devices; simulated hosts share one machine's cores "
                f"(backend={BACKEND.get('backend')})")
        record_refusal("bench.multihost_scaling", "weak_scaling_sublinear",
                       note, spec_backend="tpu-multihost")
        row["weak_scaling_gate"] = {"refused": note}
    elif ws is not None:
        assert ws["sublinear"], \
            f"cycle time grew {ws['cycle_time_growth']:.2f}x over " \
            f"{ws['domain_growth']:.0f}x domains"
        row["weak_scaling_gate"] = {"ok": True}

    # Cluster-column scoring cost at the single-chip north-star shape:
    # the fused solve with K=4 simulated remote-cluster columns vs
    # without (the marginal cost of scoring cross-cluster placement
    # inside the same execute).
    topo, usage, cohort_usage, wl = synth_solver_inputs(
        num_cqs=NUM_CQS, num_cohorts=NUM_COHORTS, num_flavors=NUM_FLAVORS,
        num_resources=NUM_RESOURCES, num_workloads=HEADS, seed=42)
    topo_dev = {k: jnp.asarray(v) for k, v in topo.items()}
    args = (jnp.asarray(usage), jnp.asarray(cohort_usage),
            jnp.asarray(wl["requests"]), jnp.asarray(wl["podset_active"]),
            jnp.asarray(wl["wl_cq"]), jnp.asarray(wl["priority"]),
            jnp.asarray(wl["timestamp"]), jnp.asarray(wl["eligible"]),
            jnp.asarray(wl["solvable"]))
    max_rank = max_rank_bound(wl["wl_cq"], topo["cq_cohort"],
                              topo["cohort_root"])
    Q, F, R = topo["nominal"].shape
    K = 4
    rng = np.random.default_rng(7)
    cargs = (jnp.asarray(rng.integers(0, 10**7, size=(K, F, R))
                         .astype(np.int64)),
             jnp.asarray(np.ones((K, F, R), bool)),
             jnp.asarray(np.ones(K, bool)),
             jnp.asarray(np.ones(Q, bool)))

    def run(with_cols):
        out = solve_cycle_fused(topo_dev, *args, num_podsets=1,
                                max_rank=max_rank,
                                cluster_args=cargs if with_cols else None)
        return int(np.asarray(out["admitted"]).sum())

    times = {}
    for with_cols in (False, True):
        run(with_cols)  # compile
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            run(with_cols)
            samples.append(time.perf_counter() - t0)
        times[with_cols] = p50(samples)
    row["cluster_columns"] = {
        "k": K,
        "solve_p50_ms": round(times[False] * 1e3, 2),
        "solve_with_columns_p50_ms": round(times[True] * 1e3, 2),
        "scoring_overhead_x": round(times[True] / max(times[False], 1e-9),
                                    3),
    }
    log(row)
    return row


def bench_sharded_admission(num_cqs=256, num_cohorts=64, backlog_waves=8,
                            layouts=(1, 2, 4, 8), budget_s=420.0):
    """ISSUE 20 row: the sharded admission control plane
    (parallel/shards.py, RESILIENCE.md §9) at storm scale — one shared
    watch/store plane, N leased admission shards draining a pre-loaded
    backlog, admitted/sec per 1/2/4/8-shard layout.

    Target scenario: 1M pending workloads x 16k CQs, shards as separate
    processes. This harness simulates shards as sequential scheduler
    instances inside ONE interpreter (the simulated-process stance the
    crash/failover benches share), so the admitted/sec SCALING gate
    over layouts is physically unwitnessable here — shards contend for
    the same core the plane runs on — and is REFUSED into the
    device-witness-debt manifest; the per-layout curve, the planner's
    layout balance and the exactly-once cross-checks are judged on
    every backend (a double admission or a lost workload fails the
    bench regardless of where it runs)."""
    from kueue_tpu.api.meta import FakeClock
    from kueue_tpu.core import workload as wlpkg
    from kueue_tpu.parallel.shards import ShardedControlPlane
    from kueue_tpu.perf.checker import record_refusal
    from kueue_tpu.sim.scenarios import _usage_consistent

    row = {
        "bench": "sharded_admission",
        "target_scenario": {"pending": 1_000_000, "cqs": 16_384,
                            "shards": list(layouts),
                            "deployment": "process-per-shard"},
        "harness": {"cqs": num_cqs, "cohorts": num_cohorts,
                    "backlog": num_cqs * backlog_waves},
    }
    total = num_cqs * backlog_waves
    curve = []
    t_start = time.perf_counter()
    for n_shards in layouts:
        clock = FakeClock(1000.0)
        scp = ShardedControlPlane(n_shards, clock=clock,
                                  checkpoint_every=4096)
        for obj in ([make_flavor("f0")]
                    + [make_cq(f"cq{i}", f"cohort-{i % num_cohorts}",
                               ["f0"], nominal_units=10 * backlog_waves)
                       for i in range(num_cqs)]
                    + [make_lq(f"lq{i}", f"cq{i}")
                       for i in range(num_cqs)]):
            scp.plane.store.create(obj)
        scp.plane.run_until_idle(max_iterations=10_000_000)
        n = 0
        for wave in range(backlog_waves):
            for i in range(num_cqs):
                scp.plane.store.create(make_workload(
                    f"s{n_shards}-w{n}", f"lq{i}", cpu_units=1,
                    creation=float(n)))
                n += 1
        scp.plane.run_until_idle(max_iterations=10_000_000)
        scp.replan()

        def admitted():
            return sum(1 for wl in scp.plane.store.list(
                "Workload", copy_objects=False)
                if wlpkg.has_quota_reservation(wl))

        cycles = 0
        t0 = time.perf_counter()
        while admitted() < total:
            scp.cycle()
            clock.advance(1.0)
            scp.renew_leases()
            cycles += 1
            if time.perf_counter() - t_start > budget_s:
                break
        wall = time.perf_counter() - t0
        got = admitted()
        assert got == total, \
            f"{n_shards}-shard layout stranded {total - got}/{total}"
        ok, msg = _usage_consistent(scp.plane)
        assert ok, f"{n_shards}-shard exactly-once cross-check: {msg}"
        shard_sum = sum(s.admitted_total for s in scp.shards)
        assert shard_sum == total, \
            f"shard counters {shard_sum} != store {total} (double count)"
        curve.append({
            "shards": n_shards,
            "admitted": got,
            "cycles": cycles,
            "wall_s": round(wall, 3),
            "admitted_per_sec": round(got / max(wall, 1e-9), 1),
            "plan_imbalance": round(scp.plan.imbalance, 3),
            "units": len(scp.plan.units),
        })
        scp.shutdown()
        assert scp.plane.cache.live_handouts == 0
    row["curve"] = curve
    base = curve[0]["admitted_per_sec"]
    row["scaling_x"] = {str(c["shards"]):
                        round(c["admitted_per_sec"] / max(base, 1e-9), 3)
                        for c in curve}
    # the planner's balance IS judged here: every layout must spread
    # cohort units within the LPT bound
    assert all(c["plan_imbalance"] <= 1.5 for c in curve), \
        f"planner imbalance out of bound: {row['scaling_x']}"
    note = ("admitted/sec scaling over shard layouts requires a "
            "process-per-shard deployment; this harness drives shards "
            "sequentially inside one interpreter (simulated-process "
            f"stance, backend={BACKEND.get('backend')}), so layout "
            "scaling is physically unwitnessable here")
    record_refusal("bench.sharded_admission", "admitted_per_sec_scaling",
                   note, spec_backend="multiprocess")
    row["scaling_gate"] = {"refused": note}
    log(row)
    return row


def main():
    import jax
    from kueue_tpu.perf import checker as checkerpkg
    from kueue_tpu.utils.runtime import ensure_live_backend
    checkerpkg.reset_witness_debt()
    BACKEND.update(ensure_live_backend(
        [sys.executable, os.path.abspath(__file__)]))
    log({"devices": [str(d) for d in jax.devices()]})

    bench_kernel()
    snapshot_speedup = bench_snapshot_incremental()
    arena_speedup = bench_workload_arena()
    bench_device_fault_recovery()
    bench_trace_overhead()
    bench_journey_overhead()
    bench_overload_shed()
    bench_scenario_slo()
    bench_visibility_storm()
    bench_cold_start()
    bench_restart_recovery()
    bench_failover_recovery()
    bench_multihost()
    bench_sharded_admission()
    hit_rate = bench_speculative_pipeline()
    rows = {}
    admitted_per_sec, speedup = bench_e2e_progressive()
    bench_transport_bytes()
    rows["progressive_fill"] = speedup
    rows["shallow"] = bench_e2e_shallow()
    rows["fair_sharing"] = bench_fair_sharing()
    rows["fair_preemption"] = bench_fair_preemption()
    rows["preemption_small"] = bench_preemption_small()
    rows["preemption_heavy"] = bench_preemption_reclaim()
    rows["cohort_depth4"] = bench_depth4_cohorts()
    # the routed system, one blended number: geometric mean over the
    # scenario mix, every device row running the production config
    # (resident state + pipelining + gates; fair_sharing row adds the
    # adaptive engine router)
    import math
    blended = math.exp(sum(math.log(v) for v in rows.values()) / len(rows))
    log({"bench": "routed_system_blended",
         "rows": {k: round(v, 2) for k, v in rows.items()},
         "blended_speedup": round(blended, 2)})

    # Device-witness debt manifest (consolidated): every rangespec this
    # run refused to judge — what a device-backend run must witness.
    debt = checkerpkg.witness_debt()
    log({"bench": "device_witness_debt", "entries": debt})

    baseline = 15000.0 / 351.1  # reference harness admitted/s, BASELINE.md
    print(json.dumps({
        "metric": "e2e_admitted_per_sec_progressive_fill_2048cq_32flavor",
        "value": round(admitted_per_sec, 1),
        "unit": "workloads/s",
        "vs_baseline": round(admitted_per_sec / baseline, 2),
        "snapshot_incremental_speedup": round(snapshot_speedup, 1),
        "workload_arena_speedup": round(arena_speedup, 1),
        "speculative_pipeline_hit_rate": round(hit_rate, 3),
        "device_witness_debt": len(debt),
        **BACKEND,
    }))


if __name__ == "__main__":
    main()
